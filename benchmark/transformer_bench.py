#!/usr/bin/env python3
"""BASELINE configs 4 + 5 throughput on one chip.

- config 4: Transformer-big (WMT14-geometry seq2seq: 1024 units, 4096 FF,
  16 heads, 6+6 layers) training tokens/sec/chip.
- config 5: GPT-2-774M (36 layers / 1280 units / 20 heads / 5120 FF —
  the geometry BASELINE.json names) single-chip train MFU.  The TP×DP
  sharding itself is validated by ``__graft_entry__.dryrun_multichip``
  on the virtual mesh; a pod is needed for real multi-chip rates.

Prints one JSON line per config.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

PEAK_TFLOPS = 197.0


def _bench_steps(trainer, mx, data, label, n_steps, reps=3):
    # one h2d transfer + device-side broadcast (tunnel is ~33 MB/s)
    import jax.numpy as jnp
    sd = mx.nd.from_jax(jnp.broadcast_to(jnp.asarray(data),
                                      (n_steps,) + data.shape))
    sl = mx.nd.from_jax(jnp.broadcast_to(jnp.asarray(label),
                                      (n_steps,) + label.shape))
    float(onp.asarray(trainer.run_steps(sd, sl).asnumpy()).reshape(-1)[0])
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        float(onp.asarray(trainer.run_steps(sd, sl).asnumpy())
              .reshape(-1)[-1])
        dt = (time.perf_counter() - t0) / n_steps
        best = dt if best is None else min(best, dt)
    return best


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    dt_str = "bfloat16" if on_tpu else "float32"
    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    rng = onp.random.RandomState(0)

    # ---- config 4: Transformer-big seq2seq --------------------------- #
    from mxnet_tpu.models import TransformerSeq2Seq as Transformer

    # seq 256 (VERDICT r3 item 9: the old bs 64 x seq 64 was a toy
    # geometry — and measured SLOWER: 36.5% MFU vs 46.4% at seq 256)
    V, L = (32768, 256) if on_tpu else (512, 16)
    B = 32 if on_tpu else 2
    mx.random.seed(0)
    net = Transformer(V, units=1024 if on_tpu else 64,
                      hidden_size=4096 if on_tpu else 128,
                      num_heads=16 if on_tpu else 4,
                      num_enc_layers=6 if on_tpu else 2,
                      num_dec_layers=6 if on_tpu else 2,
                      max_length=L, dropout=0.0, dtype=dt_str)
    net.initialize(mx.init.Xavier())

    class _Wrap(gluon.Block):
        def __init__(self):
            super().__init__()
            self.net = net

        def forward(self, both):
            src = both[:, 0]
            tgt_in = both[:, 1]
            return self.net(src, tgt_in)

    wrap = _Wrap()
    src = rng.randint(0, V, (B, L))
    tgt = rng.randint(0, V, (B, L))
    both = onp.stack([src, tgt], axis=1)               # (B, 2, L)
    trainer = parallel.SPMDTrainer(
        wrap, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-4}, mesh=mesh)
    # ≥24 steps per dispatch amortize the ~0.1 s tunnel RTT (at 8 steps
    # it added ~12 ms/step of phantom wall time)
    best = _bench_steps(trainer, mx, both, tgt, 24 if on_tpu else 2)
    toks = B * L  # target tokens per step
    # Transformer-big ≈ 213M params excl. embeddings; ~6*N flops/token
    tok_s = toks / best
    print(json.dumps({
        "bench": "transformer_big_wmt14", "tokens_per_sec_per_chip":
        round(tok_s / max(1, len(jax.devices())), 1),
        "step_ms": round(best * 1e3, 2), "batch": B, "seq": L,
        "platform": platform,
        "mfu_pct": round(100 * tok_s * 6 * 213e6 / 1e12 / PEAK_TFLOPS, 1)
        if on_tpu else None}))
    sys.stdout.flush()

    # ---- config 5: GPT-2-774M single-chip MFU ------------------------ #
    from mxnet_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=50304, max_length=512, num_layers=36,
                    units=1280, num_heads=20, hidden_size=5120,
                    dtype=dt_str) if on_tpu else \
        GPTConfig(vocab_size=512, max_length=64, num_layers=2, units=64,
                  num_heads=4, hidden_size=128)
    mx.random.seed(0)
    gpt = GPT(cfg)
    gpt.initialize(mx.init.Normal(0.02))
    B2, L2 = (4, 512) if on_tpu else (2, 16)
    toks2 = rng.randint(0, cfg.vocab_size, (B2, L2 + 1))
    trainer2 = parallel.SPMDTrainer(
        gpt, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
        {"learning_rate": 1e-4}, mesh=mesh)
    best2 = _bench_steps(trainer2, mx, toks2[:, :-1], toks2[:, 1:],
                         12 if on_tpu else 2)
    n_tok = B2 * L2
    flops_per_tok = 6 * cfg.num_params
    tok_s2 = n_tok / best2
    print(json.dumps({
        "bench": "gpt2_774m_train", "tokens_per_sec_per_chip":
        round(tok_s2 / max(1, len(jax.devices())), 1),
        "step_ms": round(best2 * 1e3, 2), "batch": B2, "seq": L2,
        "params_m": round(cfg.num_params / 1e6, 1), "platform": platform,
        "mfu_pct": round(100 * tok_s2 * flops_per_tok / 1e12 /
                         PEAK_TFLOPS, 1) if on_tpu else None}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
