#!/usr/bin/env python3
"""Multi-process pod fused-step bench: N launched CPU processes
forming one global ``jax.distributed`` mesh (gloo collectives) vs the
single-process virtual-device mesh at the SAME dp extent.

Two headline arms, printed as BENCH-format JSON rows (and mirrored to
the telemetry stream as ``bench`` events, like serve_bench):

  * ``single`` — one process, ``--xla_force_host_platform_device_count``
    giving it N virtual CPU devices: the pre-ISSUE-19 CI shape, every
    collective stays in-process.
  * ``pod`` — ``tools/launch.py -n N``: N real processes, one device
    each, the grad all-reduce compiled across process boundaries.  Per
    rank we report samples/sec and executable dispatches per step (the
    one-dispatch-per-step discipline is an assertion, not a hope).

The gap between the arms is the cost of real cross-process collectives
at equal mesh geometry — on CPU/gloo it bounds the dispatch-discipline
overhead, on a real pod it becomes the DCN/ICI number the paper's
scaling section cares about.

    python benchmark/dist_bench.py --smoke     # tier-1 geometry
    python benchmark/dist_bench.py             # bigger model, more steps

``--worker`` is the internal per-rank entry (spawned via launch.py or
directly for the single arm); it prints a ``worker`` row the
orchestrator aggregates.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def emit_row(row):
    """Stdout JSON line + telemetry ``bench`` event (serve_bench's
    dual-sink row contract, so sweep recordings carry the rows)."""
    print(json.dumps(row))
    sys.stdout.flush()
    from mxnet_tpu import telemetry
    telemetry.emit("bench", **row)


# ---------------------------------------------------------------- worker

def run_worker(args):
    """One rank of either arm: join the pod (no-op when launched solo),
    train ``--steps`` fused steps over the global mesh, report
    steady-state samples/sec and dispatches/step."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.fused_step import (reset_step_counters,
                                            step_counters)

    rank = int(os.environ.get("MXNET_WORKER_ID", "0"))
    parallel.init_distributed()
    import jax

    world = jax.process_count()
    ndev = len(jax.devices())
    local_bs = args.global_bs // world
    mesh = parallel.make_mesh({"dp": ndev})
    data_sh = parallel.data_sharding(mesh)

    mx.random.seed(11)
    onp.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(args.units, use_bias=False,
                         in_units=args.units))
        net.add(nn.Dense(args.units, use_bias=False,
                         in_units=args.units))
        net.add(nn.Dense(1, use_bias=False, in_units=args.units))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3}, kvstore=None)
    loss_l = gluon.loss.L2Loss()

    def loss_fn(bx, by):
        return loss_l(net(bx), by).mean()

    rng = onp.random.RandomState(5)
    X = rng.rand(args.global_bs, args.units).astype(onp.float32)
    Y = rng.rand(args.global_bs, 1).astype(onp.float32)
    lo, hi = rank * local_bs, (rank + 1) * local_bs
    bx = mx.nd.array(X[lo:hi])
    by = mx.nd.array(Y[lo:hi])

    # warmup = the compile; everything after is the steady state
    float(trainer.fused_step(loss_fn, bx, by, batch_size=1,
                             data_sharding=data_sh).asnumpy())
    reset_step_counters()
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = trainer.fused_step(loss_fn, bx, by, batch_size=1,
                                  data_sharding=data_sh)
    loss.asnumpy()                      # drain the dispatch chain
    wall = time.perf_counter() - t0

    row = {"worker": rank, "world": world, "devices": ndev,
           "steps": args.steps, "wall_s": round(wall, 4),
           "samples_per_sec": round(args.global_bs * args.steps / wall,
                                    1),
           "dispatches_per_step":
               step_counters["dispatches"] / args.steps,
           "compiles_steady": step_counters["compiles"]}
    print("WORKER_ROW " + json.dumps(row), flush=True)
    return 0


# ----------------------------------------------------------- orchestrator

def _worker_cmd(args):
    return [sys.executable, os.path.abspath(__file__), "--worker",
            "--steps", str(args.steps), "--units", str(args.units),
            "--global-bs", str(args.global_bs)]


def _parse_worker_rows(out):
    return [json.loads(line[len("WORKER_ROW "):])
            for line in out.splitlines()
            if line.startswith("WORKER_ROW ")]


def run_single_arm(args):
    """One process, N VIRTUAL devices: the in-process mesh baseline."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                        f"{args.procs}")
    env.pop("MXNET_TELEMETRY_JSONL", None)
    proc = subprocess.run(_worker_cmd(args), env=env, text=True,
                          capture_output=True, timeout=args.timeout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        raise SystemExit("dist_bench: single arm failed "
                         f"(exit {proc.returncode})")
    (row,) = _parse_worker_rows(proc.stdout)
    return row


def run_pod_arm(args):
    """N real processes via tools/launch.py, one device each."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env.pop("XLA_FLAGS", None)          # 1 device per rank
    env.pop("MXNET_TELEMETRY_JSONL", None)
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", str(args.procs), "--launcher", "local"] \
        + _worker_cmd(args)
    proc = subprocess.run(cmd, env=env, text=True, capture_output=True,
                          timeout=args.timeout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        sys.stderr.write(proc.stdout[-2000:])
        raise SystemExit("dist_bench: pod arm failed "
                         f"(exit {proc.returncode})")
    rows = _parse_worker_rows(proc.stdout)
    if len(rows) != args.procs:
        raise SystemExit(f"dist_bench: expected {args.procs} worker "
                         f"rows, got {len(rows)}")
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="N-process pod fused-step bench vs the "
                    "single-process virtual-mesh baseline")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 geometry (small model, few steps)")
    ap.add_argument("--procs", type=int, default=2,
                    help="pod size N (and the baseline's virtual "
                         "device count)")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--units", type=int, default=None)
    ap.add_argument("--global-bs", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    args.steps = args.steps if args.steps is not None else \
        (8 if args.smoke else 30)
    args.units = args.units if args.units is not None else \
        (64 if args.smoke else 512)
    args.global_bs = args.global_bs if args.global_bs is not None \
        else (32 if args.smoke else 256)

    if args.worker:
        return run_worker(args)

    if args.global_bs % args.procs:
        raise SystemExit("--global-bs must divide by --procs")

    single = run_single_arm(args)
    emit_row({"bench": "dist", "mode": "single", "procs": 1,
              "devices": args.procs,
              "tokens_per_sec": single["samples_per_sec"],
              "dispatches_per_step": single["dispatches_per_step"],
              "compiles_steady": single["compiles_steady"],
              "wall_s": single["wall_s"]})

    rows = run_pod_arm(args)
    worst = max(r["wall_s"] for r in rows)
    pod = {"bench": "dist", "mode": "pod", "procs": args.procs,
           "devices": args.procs,
           # the pod moves in lockstep: its throughput is the slowest
           # rank's wall clock over the same global batches
           "tokens_per_sec": round(
               args.global_bs * args.steps / worst, 1),
           "dispatches_per_step": max(r["dispatches_per_step"]
                                      for r in rows),
           "compiles_steady": max(r["compiles_steady"] for r in rows),
           "wall_s": worst}
    emit_row(pod)
    for r in rows:
        emit_row({"bench": "dist", "mode": f"pod_rank{r['worker']}",
                  **{k: v for k, v in r.items() if k != "worker"}})

    if pod["dispatches_per_step"] != 1.0 or \
            pod["compiles_steady"] != 0:
        raise SystemExit(
            "dist_bench: the pod arm broke the one-executable-per-step "
            f"discipline: {pod['dispatches_per_step']} dispatches/step, "
            f"{pod['compiles_steady']} steady-state compiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
