#!/usr/bin/env python3
"""Input-pipeline throughput proof (VERDICT r1 item 9, SURVEY.md §7
hard-part 3: host decode must feed ~11k img/s/chip for ResNet-50).

Measures the native RecordIO + libjpeg decode + threaded prefetch path at
ImageNet shapes (224×224 JPEEGs), stage by stage, and end-to-end feeding a
device step.  Prints one JSON line per stage.

    python benchmark/input_pipeline_bench.py [--n 2048] [--threads N]
"""
from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _make_rec(path, n, hw=224):
    """Write n synthetic JPEGs (structured noise, realistic entropy) into a
    .rec + .idx pair; returns mean JPEG bytes."""
    from PIL import Image
    from mxnet_tpu._native import NativeRecordWriter
    from mxnet_tpu import recordio

    rng = onp.random.RandomState(0)
    # 16 distinct source images re-encoded (keeps gen time sane); JPEG
    # decode cost depends on pixels, not uniqueness
    bufs = []
    for i in range(16):
        img = rng.rand(hw, hw, 3) * 255
        for ax in (0, 1):  # smooth → realistic JPEG size (~20-50KB)
            img = (onp.roll(img, 1, ax) + img + onp.roll(img, -1, ax)) / 3
        b = io.BytesIO()
        Image.fromarray(img.astype(onp.uint8)).save(b, format="JPEG",
                                                    quality=90)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        bufs.append(recordio.pack(header, b.getvalue()))
    w = NativeRecordWriter(path, path + ".idx")
    total = 0
    for i in range(n):
        w.write(bufs[i % 16])
        total += len(bufs[i % 16])
    w.close()
    return total / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 8)
    ap.add_argument("--hw", type=int, default=224)
    args = ap.parse_args()

    from mxnet_tpu import _native, recordio

    if not _native.available():
        print(json.dumps({"bench": "input_pipeline",
                          "error": "native IO unavailable"}))
        return 0

    def emit(stage, imgs_per_sec, **extra):
        print(json.dumps({"bench": "input_pipeline", "stage": stage,
                          "imgs_per_sec": round(imgs_per_sec, 1),
                          "threads": args.threads, **extra}))
        sys.stdout.flush()

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "bench.rec")
        mean_bytes = _make_rec(rec, args.n, args.hw)

        # stage 1: raw record read (mmap-indexed)
        r = _native.NativeRecordReader(rec, rec + ".idx")
        t0 = time.perf_counter()
        for i in range(args.n):
            r.read(i)
        dt = time.perf_counter() - t0
        emit("record_read", args.n / dt,
             mb_per_sec=round(args.n * mean_bytes / dt / 1e6, 1))

        # stage 2: single-thread unpack + JPEG decode
        t0 = time.perf_counter()
        for i in range(min(args.n, 256)):
            _h, payload = recordio.unpack(r.read(i))
            _native.decode_jpeg(payload)
        dt = time.perf_counter() - t0
        emit("decode_1thread", min(args.n, 256) / dt)

        # stage 3: threaded prefetch + decode (the training-input path)
        pf = _native.NativePrefetcher(r, list(range(args.n)),
                                      num_threads=args.threads,
                                      decode=True)
        t0 = time.perf_counter()
        cnt = 0
        for item in pf:
            cnt += 1
        dt = time.perf_counter() - t0
        emit("prefetch_decode", cnt / dt)

        # stage 4: end-to-end feeding a jitted device step (augment on
        # host, normalize+conv on device) with double buffering
        import jax
        import jax.numpy as jnp

        platform = jax.devices()[0].platform
        kernel = jnp.asarray(
            onp.random.RandomState(0).rand(8, 3, 7, 7).astype("float32"))

        @jax.jit
        def device_step(batch):
            x = batch.astype(jnp.float32) / 255.0
            from jax import lax
            dn = lax.conv_dimension_numbers(x.shape, kernel.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            return lax.conv_general_dilated(x, kernel, (2, 2),
                                            [(3, 3), (3, 3)],
                                            dimension_numbers=dn).mean()

        bs = 64
        pf = _native.NativePrefetcher(r, list(range(args.n)),
                                      num_threads=args.threads,
                                      decode=True)
        batch = onp.empty((bs, 3, args.hw, args.hw), onp.uint8)
        t0 = time.perf_counter()
        cnt = 0
        filled = 0
        pending = None
        for item in pf:
            arr = item[1] if isinstance(item, tuple) else item
            if arr.ndim == 3:
                batch[filled] = arr.transpose(2, 0, 1)
                filled += 1
            if filled == bs:
                if pending is not None:
                    pending.block_until_ready()
                pending = device_step(jnp.asarray(batch))
                cnt += bs
                filled = 0
        if pending is not None:
            float(pending)
        dt = time.perf_counter() - t0
        emit("end_to_end_device_feed", cnt / dt, platform=platform)
    return 0


if __name__ == "__main__":
    sys.exit(main())
