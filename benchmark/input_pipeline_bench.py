#!/usr/bin/env python3
"""Input-pipeline throughput proof (VERDICT r1 item 9, SURVEY.md §7
hard-part 3: host decode must feed ~11k img/s/chip for ResNet-50).

Measures the native RecordIO + libjpeg decode + threaded prefetch path at
ImageNet shapes (224×224 JPEEGs), stage by stage, and end-to-end feeding a
device step, plus the device-prefetch overlap stage (``h2d_overlap_*``
rows): steady-state step latency with the ``DevicePrefetchIter`` ring vs
the legacy synchronous path, against the input-only / compute-only
floors — with the ring, step ≈ max(input, compute).  Prints one JSON
line per stage.

    python benchmark/input_pipeline_bench.py [--n 2048] [--threads N]
    python benchmark/input_pipeline_bench.py --smoke   # tiny, no PIL/native
"""
from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _make_rec(path, n, hw=224):
    """Write n synthetic JPEGs (structured noise, realistic entropy) into a
    .rec + .idx pair; returns mean JPEG bytes."""
    from PIL import Image
    from mxnet_tpu._native import NativeRecordWriter
    from mxnet_tpu import recordio

    rng = onp.random.RandomState(0)
    # 16 distinct source images re-encoded (keeps gen time sane); JPEG
    # decode cost depends on pixels, not uniqueness
    bufs = []
    for i in range(16):
        img = rng.rand(hw, hw, 3) * 255
        for ax in (0, 1):  # smooth → realistic JPEG size (~20-50KB)
            img = (onp.roll(img, 1, ax) + img + onp.roll(img, -1, ax)) / 3
        b = io.BytesIO()
        Image.fromarray(img.astype(onp.uint8)).save(b, format="JPEG",
                                                    quality=90)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        bufs.append(recordio.pack(header, b.getvalue()))
    w = NativeRecordWriter(path, path + ".idx")
    total = 0
    for i in range(n):
        w.write(bufs[i % 16])
        total += len(bufs[i % 16])
    w.close()
    return total / n


def bench_h2d_overlap(emit, bs=64, hw=96, steps=24, depth=2):
    """Device-prefetch overlap stage: a synthetic workload where host input
    time is a measurable fraction of device compute.  Both loops block on
    the step result every iteration (the usual loss-readback pattern);
    only the ring differs — so the `overlap` row's win over `sync` is
    exactly the hidden input + H2D time.  Emits input-only and
    compute-only floors so `step ≈ max(input, compute)` is checkable."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.context import current_context
    from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter

    ctx = current_context()
    platform = jax.devices()[0].platform
    rng = onp.random.RandomState(0)
    base = (rng.rand(bs, 3, hw, hw) * 255).astype(onp.float32)
    kernel = jnp.asarray(rng.rand(8, 3, 5, 5).astype(onp.float32))

    def host_batch():
        # deliberate host work standing in for decode + augment
        img = base
        for ax in (2, 3):
            img = (onp.roll(img, 1, ax) + img + onp.roll(img, -1, ax)) / 3
        return onp.ascontiguousarray(img)

    def batches(n):
        for _ in range(n):
            yield host_batch()

    @jax.jit
    def device_step(x):
        from jax import lax
        dn = lax.conv_dimension_numbers(x.shape, kernel.shape,
                                        ("NCHW", "OIHW", "NCHW"))
        y = lax.conv_general_dilated(x / 255.0, kernel, (2, 2),
                                     [(2, 2), (2, 2)],
                                     dimension_numbers=dn)
        for _ in range(4):  # enough device work to be worth hiding behind
            y = jnp.tanh(y) + y * 0.5
        return y.mean()

    # floors: host input alone, device compute alone (resident batch)
    t0 = time.perf_counter()
    for _ in batches(steps):
        pass
    input_ms = (time.perf_counter() - t0) / steps * 1e3

    xb = jax.device_put(base)
    device_step(xb).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        device_step(xb).block_until_ready()
    compute_ms = (time.perf_counter() - t0) / steps * 1e3

    def run(ring_depth):
        it = DevicePrefetchIter(batches(steps + 2), ctx, depth=ring_depth,
                                background=ring_depth > 0)
        # warm the ring AND the executable for committed-placement inputs
        # (first call would otherwise recompile inside the timed loop)
        device_step(next(it).asjax()).block_until_ready()
        t0 = time.perf_counter()
        n = 0
        for b in it:
            device_step(b.asjax()).block_until_ready()
            n += 1
            if n == steps:
                break
        dt = (time.perf_counter() - t0) / n * 1e3
        it.close()
        return dt

    prev = os.environ.get("MXNET_DEVICE_PREFETCH")
    try:
        os.environ["MXNET_DEVICE_PREFETCH"] = "0"   # legacy synchronous
        sync_ms = run(0)
        os.environ["MXNET_DEVICE_PREFETCH"] = str(depth)
        overlap_ms = run(depth)
    finally:
        if prev is None:
            os.environ.pop("MXNET_DEVICE_PREFETCH", None)
        else:
            os.environ["MXNET_DEVICE_PREFETCH"] = prev

    common = {"platform": platform, "bs": bs, "hw": hw, "depth": depth}
    emit("h2d_input_only", bs / input_ms * 1e3, ms_per_step=round(input_ms, 2),
         **common)
    emit("h2d_compute_only", bs / compute_ms * 1e3,
         ms_per_step=round(compute_ms, 2), **common)
    emit("h2d_step_sync", bs / sync_ms * 1e3, ms_per_step=round(sync_ms, 2),
         **common)
    emit("h2d_step_overlap", bs / overlap_ms * 1e3,
         ms_per_step=round(overlap_ms, 2),
         ideal_ms=round(max(input_ms, compute_ms), 2),
         speedup_vs_sync=round(sync_ms / overlap_ms, 2), **common)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 8)
    ap.add_argument("--hw", type=int, default=224)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, overlap stage only (no PIL / native "
                         "IO requirement) — the tier-1 bit-rot gate")
    args = ap.parse_args(argv)

    def emit(stage, imgs_per_sec, **extra):
        print(json.dumps({"bench": "input_pipeline", "stage": stage,
                          "imgs_per_sec": round(imgs_per_sec, 1),
                          "threads": args.threads, **extra}))
        sys.stdout.flush()

    if args.smoke:
        bench_h2d_overlap(emit, bs=8, hw=32, steps=10, depth=2)
        return 0

    from mxnet_tpu import _native, recordio

    if not _native.available():
        print(json.dumps({"bench": "input_pipeline",
                          "error": "native IO unavailable"}))
        # stage 5 needs no native IO — still emit the overlap rows
        bench_h2d_overlap(emit, bs=64, hw=min(args.hw, 128), steps=24)
        return 0

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "bench.rec")
        mean_bytes = _make_rec(rec, args.n, args.hw)

        # stage 1: raw record read (mmap-indexed)
        r = _native.NativeRecordReader(rec, rec + ".idx")
        t0 = time.perf_counter()
        for i in range(args.n):
            r.read(i)
        dt = time.perf_counter() - t0
        emit("record_read", args.n / dt,
             mb_per_sec=round(args.n * mean_bytes / dt / 1e6, 1))

        # stage 2: single-thread unpack + JPEG decode
        t0 = time.perf_counter()
        for i in range(min(args.n, 256)):
            _h, payload = recordio.unpack(r.read(i))
            _native.decode_jpeg(payload)
        dt = time.perf_counter() - t0
        emit("decode_1thread", min(args.n, 256) / dt)

        # stage 3: threaded prefetch + decode (the training-input path)
        pf = _native.NativePrefetcher(r, list(range(args.n)),
                                      num_threads=args.threads,
                                      decode=True)
        t0 = time.perf_counter()
        cnt = 0
        for item in pf:
            cnt += 1
        dt = time.perf_counter() - t0
        emit("prefetch_decode", cnt / dt)

        # stage 4: end-to-end feeding a jitted device step (augment on
        # host, normalize+conv on device) with double buffering
        import jax
        import jax.numpy as jnp

        platform = jax.devices()[0].platform
        kernel = jnp.asarray(
            onp.random.RandomState(0).rand(8, 3, 7, 7).astype("float32"))

        @jax.jit
        def device_step(batch):
            x = batch.astype(jnp.float32) / 255.0
            from jax import lax
            dn = lax.conv_dimension_numbers(x.shape, kernel.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            return lax.conv_general_dilated(x, kernel, (2, 2),
                                            [(3, 3), (3, 3)],
                                            dimension_numbers=dn).mean()

        bs = 64
        pf = _native.NativePrefetcher(r, list(range(args.n)),
                                      num_threads=args.threads,
                                      decode=True)
        batch = onp.empty((bs, 3, args.hw, args.hw), onp.uint8)
        t0 = time.perf_counter()
        cnt = 0
        filled = 0
        pending = None
        for item in pf:
            arr = item[1] if isinstance(item, tuple) else item
            if arr.ndim == 3:
                batch[filled] = arr.transpose(2, 0, 1)
                filled += 1
            if filled == bs:
                if pending is not None:
                    pending.block_until_ready()
                pending = device_step(jnp.asarray(batch))
                cnt += bs
                filled = 0
        if pending is not None:
            float(pending)
        dt = time.perf_counter() - t0
        emit("end_to_end_device_feed", cnt / dt, platform=platform)

    # stage 5: device-prefetch ring vs legacy synchronous feed
    bench_h2d_overlap(emit, bs=64, hw=min(args.hw, 128), steps=24)
    return 0


if __name__ == "__main__":
    sys.exit(main())
