#!/usr/bin/env python3
"""BERT train-step component breakdown on TPU (VERDICT r1 item 2: publish a
per-component breakdown and close the MFU gap).

Times each component with the chained-scan methodology (outputs feed the
next iteration so XLA cannot hoist; in-dispatch reps sized so the tunnel
round-trip is noise).  Prints one JSON line per component.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

PEAK_TFLOPS = 197.0  # v5e bf16


def emit_fused_step_rows(platform, smoke=False):
    """Section 8: the whole train step as ONE donated-buffer executable
    (``Trainer.fused_step``) vs the phase-by-phase chain, with the
    gradient-accumulation window sweep — methodology shared with
    step_profile (``measure_fused_step``)."""
    from benchmark.step_profile import measure_fused_step
    kw = dict(n_layers=8, units=8, bs=4, reps=3, intervals=(1, 2),
              warm=2) if smoke else {}
    n, rows = measure_fused_step(**kw)
    for mode, disp, dt in rows:
        name = "train_step_phase" if mode.startswith("phase") else \
            "train_step_fused_" + mode.split("N=")[-1].strip()
        print(json.dumps({
            "bench": "step_breakdown",
            "component": name,
            "ms": round(dt, 3),
            "params": n,
            "host_dispatches_per_step": round(disp),
            "platform": platform}))
        sys.stdout.flush()


def main():
    import argparse

    import jax
    import jax.numpy as jnp
    from jax import lax

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fused-step section only, tiny sizes (tier-1 "
                         "gate)")
    args = ap.parse_args()
    platform = jax.devices()[0].platform
    if args.smoke:
        emit_fused_step_rows(platform, smoke=True)
        return 0
    B, L, U, H, FF, V = 64, 128, 768, 12, 3072, 30528
    NL = 12
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    tokens = B * L

    def bench(fn, *args, feed_index=0):
        """ms/op via chained scan with adaptive rep count."""
        def make(inner):
            @jax.jit
            def looped(x0, *rest):
                def body(c, _):
                    out = fn(c, *rest)
                    nxt = out[feed_index] if isinstance(out, tuple) else out
                    return nxt.astype(x0.dtype) if nxt.shape == x0.shape \
                        else x0 + 0 * jnp.sum(nxt).astype(x0.dtype), None
                c, _ = lax.scan(body, x0, None, length=inner)
                return jnp.sum(c.astype(jnp.float32))
            return looped

        cal = make(8)
        float(cal(*args))
        t0 = time.perf_counter()
        float(cal(*args))
        est = (time.perf_counter() - t0) / 8
        inner = max(8, min(2048, int(2.0 / max(est, 1e-5))))
        run = make(inner)
        float(run(*args))
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            float(run(*args))
            times.append(time.perf_counter() - t0)
        return min(times) / inner * 1e3

    def emit(name, ms, gflop=None):
        rec = {"bench": "step_breakdown", "component": name,
               "ms": round(ms, 3), "platform": platform}
        if gflop:
            rec["tflops"] = round(gflop / ms, 2)
            rec["mfu_pct"] = round(100 * gflop / ms / PEAK_TFLOPS, 1)
        print(json.dumps(rec))
        sys.stdout.flush()

    rng = onp.random.RandomState(0)
    x = jnp.asarray(rng.randn(tokens, U), dtype)

    # 1. FFN chain fwd: NL x (U->FF gelu FF->U)
    w1 = jnp.asarray(rng.randn(U, FF) * 0.02, dtype)
    w2 = jnp.asarray(rng.randn(FF, U) * 0.02, dtype)

    def ffn_fwd(h):
        for _ in range(NL):
            h = jax.nn.gelu(h @ w1) @ w2
        return h

    g_ffn = 2 * tokens * U * FF * 2 * NL / 1e9
    emit("ffn_chain_fwd(12x)", bench(ffn_fwd, x), g_ffn)

    # 2. FFN chain fwd+bwd
    def ffn_loss(h):
        return jnp.sum(ffn_fwd(h).astype(jnp.float32))
    emit("ffn_chain_fwd+bwd(12x)", bench(jax.grad(ffn_loss), x),
         g_ffn * 3)

    # 3. attention fwd+bwd at seq 128 (plain path, as the bench model uses)
    from mxnet_tpu.ops import attention as attn
    qh = jnp.asarray(rng.randn(B, H, L, U // H), dtype)

    def attn_all(q):
        out = q
        for _ in range(NL):
            out = attn._plain_attn(out, out, out, None, 0.125, False)
        return out
    g_attn = 4 * B * H * L * L * (U // H) * NL / 1e9
    emit("attention_fwd(12x,seq128)", bench(attn_all, qh), g_attn)

    def attn_loss(q):
        return jnp.sum(attn_all(q).astype(jnp.float32))
    emit("attention_fwd+bwd(12x)", bench(jax.grad(attn_loss), qh),
         g_attn * 3.5)

    # 4. MLM head: logits matmul + softmax-CE fwd+bwd
    wv = jnp.asarray(rng.randn(U, V) * 0.02, dtype)
    labels = jnp.asarray(rng.randint(0, V, (tokens,)), jnp.int32)

    def head_loss(h):
        logits = (h @ wv).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], 1))
    g_head = 2 * tokens * U * V * 3 / 1e9
    emit("mlm_head_fwd+bwd", bench(jax.grad(head_loss), x), g_head)

    # 5. AdamW update on a BERT-sized param set (~110M fp32 master+states)
    nparams = 110_000_000
    w = jnp.zeros((nparams // 64, 64), dtype)
    m = jnp.zeros(w.shape, jnp.float32)
    v = jnp.zeros(w.shape, jnp.float32)
    master = jnp.zeros(w.shape, jnp.float32)
    gbuf = jnp.asarray(rng.randn(*w.shape) * 1e-3, dtype)

    def adamw(g, m, v, master):
        g32 = g.astype(jnp.float32)
        m2 = 0.9 * m + 0.1 * g32
        v2 = 0.999 * v + 0.001 * g32 * g32
        mast2 = master - 1e-4 * (m2 / (jnp.sqrt(v2) + 1e-8) + 0.01 * master)
        return g, m2, v2, mast2
    emit("adamw_update(110M,mp)", bench(adamw, gbuf, m, v, master))

    # 6. full train step via SPMDTrainer (the bench.py path), per-step
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import BERTModel, BERTConfig
    mx.random.seed(0)
    cfg = BERTConfig(vocab_size=V, max_length=L, num_layers=NL, units=U,
                     num_heads=H, hidden_size=FF,
                     dtype="bfloat16" if platform == "tpu" else "float32")
    bert = BERTModel(cfg, use_pooler=False, use_mlm=True)

    class _Head(gluon.Block):
        def __init__(self):
            super().__init__()
            self.bert = bert

        def forward(self, tokens):
            return self.bert(tokens)[-1]

    net = _Head()
    net.initialize(mx.init.Normal(0.02))
    trainer = parallel.SPMDTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                   "adamw", {"learning_rate": 1e-4},
                                   mesh=parallel.make_mesh(
                                       {"dp": len(jax.devices())}))
    toks = rng.randint(0, V, (B, L))
    labs = rng.randint(0, V, (B, L))

    # 6a. model-only ablation: loss fwd and fwd+bwd through the full BERT
    # (no optimizer, no scan) — isolates where the step's non-matmul time
    # lives
    g_step = (g_ffn + g_attn + 2 * tokens * U * V / 1e9 +
              2 * tokens * 4 * U * U * NL / 1e9) * 3
    trainer._ensure_built(mx.nd.array(toks), mx.nd.array(labs))
    tv = tuple(trainer._train_vals)
    fv = list(trainer._frozen_vals)
    d32 = jnp.asarray(toks)
    l32 = jnp.asarray(labs)
    key0 = jax.random.PRNGKey(0)

    def loss_only(tv_q, d, l):
        box = []
        return trainer._forward_loss(key0, tv_q, fv, d, l, box)

    @jax.jit
    def fwd_rep(d, l):
        def body(c, _):
            return c + loss_only(tv, d, l), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=8)
        return c

    @jax.jit
    def fwdbwd_rep(d, l):
        # grads must feed the next iteration's params or XLA DCEs the
        # whole backward — a 1e-12-lr SGD keeps it alive at ~zero cost
        def body(c_tv, _):
            lv, gr = jax.value_and_grad(
                lambda t: loss_only(t, d, l))(c_tv)
            new_tv = tuple(v - g.astype(v.dtype) * 1e-12
                           for v, g in zip(c_tv, gr))
            return new_tv, lv
        tv_out, losses = jax.lax.scan(body, tv, None, length=8)
        return losses[-1] + jnp.sum(tv_out[0].astype(jnp.float32)) * 0 + \
            sum(jnp.sum(t.astype(jnp.float32)) for t in tv_out) * 1e-12

    for nm, f, mult in (("model_fwd_only", fwd_rep, 1),
                        ("model_fwd+bwd_sgd1e-12", fwdbwd_rep, 3)):
        float(f(d32, l32))
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            float(f(d32, l32))
            ts.append(time.perf_counter() - t0)
        emit(nm, min(ts) / 8 * 1e3, g_step / 3 * mult)

    n_steps = 20
    sd = mx.nd.array(onp.broadcast_to(toks, (n_steps,) + toks.shape))
    sl = mx.nd.array(onp.broadcast_to(labs, (n_steps,) + labs.shape))
    float(onp.asarray(trainer.run_steps(sd, sl).asnumpy()).reshape(-1)[0])
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        float(onp.asarray(trainer.run_steps(sd, sl).asnumpy())
              .reshape(-1)[-1])
        dt = (time.perf_counter() - t0) / n_steps
        best = dt if best is None else min(best, dt)
    emit("full_train_step", best * 1e3, g_step)
    print(json.dumps({"bench": "step_breakdown",
                      "component": "throughput",
                      "tokens_per_sec": round(tokens / best, 1),
                      "platform": platform}))

    # 7. optimizer-apply phase on the IMPERATIVE Trainer path: the fused
    # multi-tensor apply issues O(#groups) jitted dispatches per step vs
    # the legacy O(#params) loop — both timed on the same BERT param set
    # with synthetic grads (the phase under test is the apply itself;
    # measurement methodology shared with step_profile)
    from benchmark.step_profile import measure_optimizer_apply
    n, rows = measure_optimizer_apply(net.collect_params(), "adamw")
    for mode, disp, dt in rows:
        print(json.dumps({
            "bench": "step_breakdown",
            "component": f"optimizer_apply_{mode}",
            "ms": round(dt, 3),
            "params": n,
            "apply_dispatches_per_step": round(disp),
            "platform": platform}))
        sys.stdout.flush()

    # 8. fused train step: fwd+bwd+apply as ONE executable, accumulate
    # window sweep
    emit_fused_step_rows(platform)
    return 0


if __name__ == "__main__":
    sys.exit(main())
