#!/usr/bin/env python3
"""Fused 1x1 conv-bwd Pallas kernel vs XLA's dgrad+wgrad pair, per
ResNet-50 1x1 shape — the VERDICT r4 item 1 kill measurement
(BASELINE.md "conv-bwd kill" has the analysis).

Harness notes (hard-won, r5):
- the slope method needs >= ~0.5 s of device work between the two trip
  counts or the tunnel's ~100 ms RTT jitter swamps the signal;
- XLA's algebraic simplifier defeats naive consumption: sum(dx) pushes
  THROUGH a matmul (sum(dy@w) = contract-then-tiny), and even
  sum((s*dy@w)^2) hoists the loop-invariant part via the scalar rule —
  the XLA arm varies the input by DYNAMIC SLICE (no algebraic escape);
- the Pallas arm scales dy INSIDE the kernel (opaque to XLA) so the
  variance costs no HBM traffic, and consumes one element per output
  (a pallas_call cannot be narrowed).

  python benchmark/conv_fused_bench.py [--bs 256] [--only s1]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PEAK_TF = 197.0
HBM_GBS = 819.0
PREC = lax.Precision.DEFAULT


def shapes(bs):
    # (name, hw, ci, co) for every stride-1 1x1 of ResNet-50 v1
    return [("s1_1x1r", 56, 256, 64), ("s1_1x1e", 56, 64, 256),
            ("s2_1x1r", 28, 512, 128), ("s2_1x1e", 28, 128, 512),
            ("s3_1x1r", 14, 1024, 256), ("s3_1x1e", 14, 256, 1024),
            ("s4_1x1r", 7, 2048, 512), ("s4_1x1e", 7, 512, 2048)]


def slope(f, args, n1=5):
    """Pilot with an RTT-cancelling delta (T(5*n1)-T(n1)) — a plain
    T(n1)/n1 pilot is RTT-dominated for sub-ms ops and under-sizes n2
    (the r5 "0.000 ms" rows)."""
    float(f(n1, *args))
    t1 = time.time(); float(f(n1, *args)); t1 = time.time() - t1
    t5 = time.time(); float(f(5 * n1, *args)); t5 = time.time() - t5
    per_it = max((t5 - t1) / (4 * n1), 2e-5)
    n2 = n1 + max(500, min(20000, int(0.8 / per_it)))
    best = {}
    for n in (n1, n2):
        b = None
        for _ in range(3):
            t0 = time.time()
            float(f(n, *args))
            dt = time.time() - t0
            b = dt if b is None else min(b, dt)
        best[n] = b
    return max((best[n2] - best[n1]) / (n2 - n1), 1e-9)


def pallas_pair_call(p, ci, co, tp):
    grid = p // tp

    def kern(s_ref, dy_ref, x_ref, w_ref, dx_ref, dw_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            dw_ref[:] = jnp.zeros_like(dw_ref)
        d = dy_ref[:] * s_ref[0, 0]
        dx_ref[:] = jnp.dot(d, w_ref[:], precision=PREC,
                            preferred_element_type=jnp.float32
                            ).astype(dx_ref.dtype)
        dw_ref[:] += jnp.dot(d.T, x_ref[:], precision=PREC,
                             preferred_element_type=jnp.float32)

    def call(s, dy, x, w):
        return pl.pallas_call(
            kern, grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((tp, co), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((tp, ci), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((co, ci), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)],
            out_specs=[
                pl.BlockSpec((tp, ci), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((co, ci), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)],
            out_shape=[jax.ShapeDtypeStruct((p, ci), jnp.bfloat16),
                       jax.ShapeDtypeStruct((co, ci), jnp.float32)],
        )(s, dy, x, w)
    return call


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=256)
    ap.add_argument("--only", default="")
    args = ap.parse_args()
    import numpy as onp

    from mxnet_tpu.ops.conv_fused import _pick_tile

    rng = onp.random.RandomState(0)
    rows = []
    print(f"{'shape':10s} | {'xla pair ms':>11s} | {'pallas ms':>9s} | "
          f"{'xla 2-read roof':>15s} | {'fused 1-read roof':>17s} | "
          f"{'tp':>5s}")
    for name, hw, ci, co in shapes(args.bs):
        if args.only and args.only not in name:
            continue
        p = args.bs * hw * hw
        dyb = jnp.asarray(rng.rand(p + 8, co) - 0.5, jnp.bfloat16)
        dy = dyb[:p]
        x = jnp.asarray(rng.rand(p, ci) - 0.5, jnp.bfloat16)
        w = jnp.asarray(rng.rand(co, ci) - 0.5, jnp.bfloat16)

        def xla_run(n, dyb_, x_, w_):
            def body(i, acc):
                d = lax.dynamic_slice(dyb_, (i % 8, 0), (p, co))
                dx = jnp.dot(d, w_, precision=PREC,
                             preferred_element_type=jnp.float32
                             ).astype(jnp.bfloat16)
                dw = lax.dot_general(
                    d, x_, (((0,), (0,)), ((), ())), precision=PREC,
                    preferred_element_type=jnp.float32)
                return acc + jnp.sum((dx * dx).astype(jnp.float32)) \
                    + jnp.sum(dw * dw)
            return lax.fori_loop(0, n, body, jnp.float32(0))

        tp = _pick_tile(p, ci, co)
        t_p = None
        if tp:
            call = pallas_pair_call(p, ci, co, tp)

            def pallas_run(n, ones, dy_, x_, w_):
                def body(i, acc):
                    s = ones[i % 8].reshape(1, 1)
                    dx, dw = call(s, dy_, x_, w_)
                    return acc + dx[0, 0].astype(jnp.float32) + dw[0, 0]
                return lax.fori_loop(0, n, body, jnp.float32(0))

            ones = jnp.ones((8,), jnp.bfloat16)
            # tracelint: disable=TL003 -- bench sweep: each loop iteration times a DIFFERENT shape config, one jit each is the point
            t_p = slope(jax.jit(pallas_run), (ones, dy, x, w))
        # tracelint: disable=TL003 -- bench sweep: each loop iteration times a DIFFERENT shape config, one jit each is the point
        t_x = slope(jax.jit(xla_run), (dyb, x, w))
        roof2 = (2 * p * co + 2 * p * ci) * 2 / HBM_GBS / 1e9
        roof1 = (p * co + 2 * p * ci) * 2 / HBM_GBS / 1e9
        row = {"name": name, "p": p, "ci": ci, "co": co, "tp": tp,
               "xla_ms": t_x * 1e3,
               "pallas_ms": t_p * 1e3 if t_p else None,
               "xla_roof_ms": roof2 * 1e3, "fused_roof_ms": roof1 * 1e3}
        rows.append(row)
        print(f"{name:10s} | {row['xla_ms']:11.3f} | "
              f"{(row['pallas_ms'] or -1):9.3f} | {roof2 * 1e3:15.3f} | "
              f"{roof1 * 1e3:17.3f} | {tp:5d}")
    with open("/tmp/conv_fused_bench.json", "w") as fh:
        json.dump(rows, fh, indent=1)
    print("wrote /tmp/conv_fused_bench.json")


if __name__ == "__main__":
    main()
