#!/usr/bin/env python3
"""Flash-attention length benchmark: Pallas kernels vs XLA paths.

VERDICT r1 item 3: "a seq-512/2k/8k fwd+bwd TPU benchmark proving the
kernel beats _plain_attn/XLA at length".  Prints one JSON line per
(seq_len, impl, pass) with ms and achieved TFLOP/s; run on the TPU chip:

    python benchmark/attention_bench.py

Timing uses a device->host readback as the sync point (tunnel-safe, same
methodology as bench.py) and amortizes dispatch by looping the op inside
one jit via lax.scan.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mxnet_tpu.ops import attention as attn

    platform = jax.devices()[0].platform
    B, H, D = 4, 8, 64
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32

    def bench(fn, *args):
        """Adaptive timing: calibrate with a short run, then size the
        in-dispatch rep count so device work (~2.5 s) dwarfs the tunnel
        round-trip (observed 13-120 ms, unstable).  Each iteration feeds
        its first output back as the first input (same (B,H,L,D) shape)
        so XLA cannot hoist the loop-invariant op out of the scan."""
        def make(inner):
            @jax.jit
            def looped(q0, *rest):
                def body(c, _):
                    out = fn(c, *rest)
                    nxt = out[0] if isinstance(out, tuple) else out
                    return nxt.astype(q0.dtype), None
                c, _ = lax.scan(body, q0, None, length=inner)
                return jnp.sum(c.astype(jnp.float32))
            return looped

        cal = make(16)
        float(cal(*args))  # compile + warmup
        t0 = time.perf_counter()
        float(cal(*args))
        est = (time.perf_counter() - t0) / 16
        inner = max(16, min(4096, int(2.5 / max(est, 1e-5))))
        run = make(inner)
        float(run(*args))  # compile
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            float(run(*args))  # readback syncs
            times.append(time.perf_counter() - t0)
        return min(times) / inner * 1e3

    def emit(seq, impl, pas, ms):
        # fwd: 2 matmuls (QK^T, PV) = 4*B*H*L^2*D flops; bwd ~2.5x fwd
        flops = 4 * B * H * seq * seq * D * (1 if pas == "fwd" else 3.5)
        print(json.dumps({
            "bench": "flash_attention", "seq": seq, "impl": impl,
            "pass": pas, "ms": round(ms, 3),
            "tflops": round(flops / ms / 1e9, 2),
            "platform": platform}))
        sys.stdout.flush()

    for seq in (512, 2048, 8192):
        rng = onp.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(B, H, seq, D), dtype)
                   for _ in range(3))
        scale = 1.0 / D ** 0.5

        impls = {}
        if platform == "tpu":
            impls["pallas"] = functools.partial(
                attn._pallas_fwd, scale=scale, causal=True)
        impls["xla_blockwise"] = lambda q, k, v: attn._blockwise_attn(
            q, k, v, None, jnp.uint32(0), scale, True, 0.0, 128)
        if seq <= 2048:  # plain materializes O(L^2); OOM-prone at 8k
            impls["plain"] = functools.partial(
                attn._plain_attn, bias=None, scale=scale, causal=True)

        for name, fn in impls.items():
            emit(seq, name, "fwd", bench(fn, q, k, v))

        # fwd+bwd through the public custom-vjp path vs plain autodiff
        def flash_loss(q, k, v):
            return jnp.sum(
                attn._flash(q, k, v, None, jnp.uint32(0), scale, True)
                .astype(jnp.float32))

        def plain_loss(q, k, v):
            return jnp.sum(
                attn._plain_attn(q, k, v, None, scale, True)
                .astype(jnp.float32))

        emit(seq, "flash(custom-vjp)", "fwd+bwd",
             bench(jax.grad(flash_loss, argnums=(0, 1, 2)), q, k, v))
        if seq <= 2048:
            emit(seq, "plain", "fwd+bwd",
                 bench(jax.grad(plain_loss, argnums=(0, 1, 2)), q, k, v))
    return 0


if __name__ == "__main__":
    sys.exit(main())
