#!/usr/bin/env python3
"""Flash-attention length sweep: every path, fwd AND fwd+bwd.

VERDICT r2 item 4: the op's dispatch must follow the measurements — this
sweep measures all three implementations (plain materialized, XLA
blockwise, Pallas kernel) at seq 512/1024/2048/4096/8192, forward and
train (fwd+bwd), and prints one JSON line per point.  The crossover
constants in ``ops/attention.py`` (``_PATH_TABLE``) are derived from this
table; ``tests/test_attention.py`` asserts the dispatch matches it.

    python benchmark/attention_bench.py            # full sweep
    python benchmark/attention_bench.py --seqs 512,2048

Timing uses a device->host readback as the sync point (tunnel-safe, same
methodology as bench.py) and amortizes dispatch by looping the op inside
one jit via lax.scan.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="512,1024,2048,4096,8192")
    ap.add_argument("--budget", type=float, default=1.5,
                    help="target device-seconds per timed dispatch")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mxnet_tpu.ops import attention as attn

    platform = jax.devices()[0].platform
    B, H, D = 4, 8, 64
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32

    def bench(fn, *args_):
        """Adaptive timing: calibrate with a short run, then size the
        in-dispatch rep count so device work dwarfs the tunnel round-trip
        (observed 13-120 ms, unstable).  Each iteration feeds its first
        output back as the first input (same (B,H,L,D) shape) so XLA
        cannot hoist the loop-invariant op out of the scan."""
        def make(inner):
            @jax.jit
            def looped(q0, *rest):
                def body(c, _):
                    out = fn(c, *rest)
                    nxt = out[0] if isinstance(out, tuple) else out
                    return nxt.astype(q0.dtype), None
                c, _ = lax.scan(body, q0, None, length=inner)
                return jnp.sum(c.astype(jnp.float32))
            return looped

        cal = make(8)
        float(cal(*args_))  # compile + warmup
        t0 = time.perf_counter()
        float(cal(*args_))
        est = (time.perf_counter() - t0) / 8
        inner = max(8, min(4096, int(args.budget / max(est, 1e-5))))
        run = make(inner)
        float(run(*args_))  # compile
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            float(run(*args_))  # readback syncs
            times.append(time.perf_counter() - t0)
        return min(times) / inner * 1e3

    results = {}

    def emit(seq, impl, pas, ms):
        # fwd: 2 matmuls (QK^T, PV) = 4*B*H*L^2*D flops; bwd ~2.5x fwd
        flops = 4 * B * H * seq * seq * D * (1 if pas == "fwd" else 3.5)
        results[(seq, impl, pas)] = ms
        print(json.dumps({
            "bench": "flash_attention", "seq": seq, "impl": impl,
            "pass": pas, "ms": round(ms, 3),
            "tflops": round(flops / ms / 1e9, 2),
            "platform": platform}))
        sys.stdout.flush()

    def force_pallas(on):
        """Monkeypatch the trace-time path predicate (dispatch happens at
        trace time, so this reliably selects the implementation)."""
        attn._use_pallas_saved = getattr(attn, "_use_pallas_saved",
                                         attn._use_pallas)
        attn._use_pallas = (attn._use_pallas_saved if on
                            else (lambda: False))

    scale = 1.0 / D ** 0.5
    for seq in [int(s) for s in args.seqs.split(",")]:
        rng = onp.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(B, H, seq, D), dtype)
                   for _ in range(3))

        # ---------------- forward ----------------
        if platform == "tpu":
            force_pallas(True)
            emit(seq, "pallas", "fwd", bench(functools.partial(
                attn._pallas_fwd, scale=scale, causal=True), q, k, v))
        emit(seq, "xla_blockwise", "fwd", bench(
            lambda q, k, v: attn._blockwise_attn(
                q, k, v, None, jnp.uint32(0), scale, True, 0.0, 128),
            q, k, v))
        if seq <= 4096:  # plain materializes O(L^2); OOM-prone past 4k
            emit(seq, "plain", "fwd", bench(functools.partial(
                attn._plain_attn, bias=None, scale=scale, causal=True),
                q, k, v))

        # ---------------- fwd+bwd ----------------
        def flash_loss(q, k, v):
            return jnp.sum(
                attn._flash(q, k, v, None, jnp.uint32(0), scale, True)
                .astype(jnp.float32))

        def plain_loss(q, k, v):
            return jnp.sum(
                attn._plain_attn(q, k, v, None, scale, True)
                .astype(jnp.float32))

        if platform == "tpu":
            force_pallas(True)
            emit(seq, "pallas", "fwd+bwd",
                 bench(jax.grad(flash_loss, argnums=(0, 1, 2)), q, k, v))
        force_pallas(False)
        emit(seq, "xla_blockwise", "fwd+bwd",
             bench(jax.grad(flash_loss, argnums=(0, 1, 2)), q, k, v))
        force_pallas(True)
        if seq <= 4096:
            emit(seq, "plain", "fwd+bwd",
                 bench(jax.grad(plain_loss, argnums=(0, 1, 2)), q, k, v))

    # summary: fastest impl per (seq, pass)
    best = {}
    for (seq, impl, pas), ms in results.items():
        k_ = (seq, pas)
        if k_ not in best or ms < best[k_][1]:
            best[k_] = (impl, ms)
    print(json.dumps({"bench": "flash_attention_best",
                      "best": {f"{s}/{p}": i for (s, p), (i, _)
                               in sorted(best.items())}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
