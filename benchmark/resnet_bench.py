#!/usr/bin/env python3
"""ResNet-50 ImageNet-shape training throughput (BASELINE config 2:
images/sec/chip, synthetic device-resident data — the reference's
``train_imagenet.py --benchmark 1`` dummy-data mode).

Prints one JSON line.  ResNet-50 fwd ≈ 4.1 GFLOP/img at 224²; train ≈ 3×.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

PEAK_TFLOPS = 197.0
# ResNet-50 fwd ~= 4.1 GMACs = 8.2 GFLOP/img at 224^2 (2 flops per
# multiply-add; cross-checked against XLA's own model_flops in the step
# trace: 7.4 GFLOP/img conv-only fwd, 22.2 train).  Train ~= 3x fwd.
# The r1/r2 bench used 4.1 GFLOP here — counting MACs as FLOPs — which
# UNDERSTATED MFU by 2x (the r2 "12.7% MFU" was really ~25%).
GFLOP_PER_IMG_TRAIN = 8.2 * 3


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    bs = int(os.environ.get("RESNET_BS", "128")) if on_tpu else 4
    hw = 224 if on_tpu else 32
    mx.random.seed(0)

    # NCHW default: measured FASTER end-to-end than NHWC on this chip
    # (r5: 99.7 vs 103.3 ms/step — XLA's internal conv relayout beats
    # the whole-stack channels-last graph); NHWC selectable for A/B
    layout = os.environ.get("RESNET_LAYOUT", "NCHW")
    net = get_resnet(1, 50, classes=1000, layout=layout)
    net.initialize(mx.init.Xavier())
    if on_tpu:
        net.cast("bfloat16")
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        mesh=parallel.make_mesh({"dp": len(jax.devices())}))

    rng = onp.random.RandomState(0)
    x = rng.rand(bs, 3, hw, hw).astype(
        "bfloat16" if on_tpu else "float32")
    y = rng.randint(0, 1000, bs).astype(onp.float32)
    # ≥30 steps per dispatch: the fixed ~0.1 s tunnel RTT cost ~10 ms of
    # phantom wall time per step at n=10 (see BASELINE.md r4 methodology)
    n_steps = 30 if on_tpu else 2
    # transfer ONE batch, broadcast device-side: 30 host copies would
    # ship ~1 GB over the ~33 MB/s tunnel for identical data
    import jax.numpy as jnp
    sd = mx.nd.from_jax(jnp.broadcast_to(jnp.asarray(x), (n_steps,) + x.shape))
    sl = mx.nd.from_jax(jnp.broadcast_to(jnp.asarray(y), (n_steps,) + y.shape))
    # compile + warmup, then best-of-3 fused multi-step scans
    float(onp.asarray(trainer.run_steps(sd, sl).asnumpy()).reshape(-1)[0])
    best = None
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        float(onp.asarray(trainer.run_steps(sd, sl).asnumpy())
              .reshape(-1)[-1])
        dt = (time.perf_counter() - t0) / n_steps
        best = dt if best is None else min(best, dt)

    imgs = bs / best / max(1, len(jax.devices()))
    rec = {"bench": "resnet50_train", "imgs_per_sec_per_chip":
           round(imgs, 1), "step_ms": round(best * 1e3, 2),
           "batch": bs, "hw": hw, "layout": layout,
           "platform": platform}
    if on_tpu:
        rec["mfu_pct"] = round(
            100 * imgs * GFLOP_PER_IMG_TRAIN / 1e3 / PEAK_TFLOPS, 1)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
