#!/usr/bin/env python3
"""opperf — per-operator timing harness over the full registry.

Reference surface: ``benchmark/opperf/`` (SURVEY.md §6 "benchmark
machinery": per-operator timing harness over the full registry).

Times each registered op's eager dispatch and, separately, its jitted
steady-state (the compiled-kernel cost, what actually matters on TPU).
Synchronization uses a device→host readback — reliable on tunneled
backends where block_until_ready returns early.

Usage::

    python benchmark/opperf/opperf.py                # all default-profiled ops
    python benchmark/opperf/opperf.py --ops dot relu softmax
    python benchmark/opperf/opperf.py --json out.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

# runnable from any cwd: the repo root is two levels up
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


# shapes per op family; (args builder) -> list of jax arrays
def _default_inputs(name, rng, large):
    import jax.numpy as jnp
    n = 1024 if large else 128
    sq = (n, n)
    vec = (n * n,)
    mk = lambda shape: jnp.asarray(rng.rand(*shape).astype(onp.float32))
    specials = {
        "dot": lambda: [mk(sq), mk(sq)],
        "matmul": lambda: [mk(sq), mk(sq)],
        "batch_dot": lambda: [mk((8,) + sq), mk((8,) + sq)],
        "linalg_gemm2": lambda: [mk(sq), mk(sq)],
        "FullyConnected": lambda: ([mk(sq), mk(sq)],
                                   {"num_hidden": n, "no_bias": True}),
        "Convolution": lambda: ([mk((8, 16, 32, 32)),
                                 mk((32, 16, 3, 3))],
                                {"kernel": (3, 3), "num_filter": 32,
                                 "no_bias": True}),
        "Pooling": lambda: ([mk((8, 16, 32, 32))],
                            {"kernel": (2, 2), "pool_type": "max"}),
        "concat": lambda: [mk(sq), mk(sq)],
        "take": lambda: [mk(sq), jnp.asarray(
            rng.randint(0, n, 64).astype(onp.int32))],
        "one_hot": lambda: ([jnp.asarray(rng.randint(0, n, vec[0] // n)
                                         .astype(onp.int32))],
                            {"depth": n}),
        "Embedding": lambda: ([jnp.asarray(rng.randint(0, n, (64,))
                                           .astype(onp.int32)), mk(sq)],
                              {"input_dim": n, "output_dim": n}),
        "LayerNorm": lambda: [mk(sq), mk((n,)), mk((n,))],
        "RMSNorm": lambda: [mk(sq), mk((n,))],
        "softmax": lambda: [mk(sq)],
        "topk": lambda: ([mk(sq)], {"k": 8}),
        "sort": lambda: [mk(sq)],
        "argsort": lambda: [mk(sq)],
        "flash_attention": lambda: [mk((4, 8, 256, 64)), mk((4, 8, 256, 64)),
                                    mk((4, 8, 256, 64))],
    }
    if name in specials:
        out = specials[name]()
        return out if isinstance(out, tuple) else (out, {})
    return [mk(sq)], {}


_SKIP = {
    # need structured inputs not worth synthesizing here
    "fused_rnn", "CTCLoss", "ring_attention", "sequence_last",
    "sequence_mask", "sequence_reverse", "boolean_mask", "gather_nd",
    "scatter_nd", "where", "pick", "_DropoutImpl", "_BatchNormStats",
    "broadcast_like", "slice", "slice_axis", "slice_like", "split",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "_contrib_dequantize", "_contrib_requantize", "quantized_matmul_int8",
    "repeat", "tile", "pad", "expand_dims", "reshape", "diag",
    "SoftmaxOutput", "MakeLoss", "InstanceNorm", "GroupNorm", "Deconvolution",
    "L2Normalization", "LeakyReLU", "Activation", "SoftmaxActivation",
    "amp_multicast", "multi_all_finite", "add_n", "stack",
    "broadcast_axis", "broadcast_to", "full_like", "one_hot", "cast",
    "arctan2", "broadcast_hypot",
}


def run_op_benchmark(names=None, warmup=2, runs=10, large=False):
    import jax

    from mxnet_tpu.ops import registry
    import mxnet_tpu.ndarray  # noqa: F401 — populate registry

    rng = onp.random.RandomState(7)
    results = []
    all_names = names or [n for n in registry.list_ops() if n not in _SKIP]
    for name in all_names:
        opref = registry.get_op(name)
        try:
            arrays, kwargs = _default_inputs(name, rng, large)
            fn = lambda *xs: opref.fn(*xs, **kwargs)
            # tracelint: disable=TL003 -- opperf times one fresh executable per op by design; fn differs every iteration
            jitted = jax.jit(fn)
            # correctness/compile check
            out = jitted(*arrays)
            onp.asarray(jax.device_get(
                out[0] if isinstance(out, (tuple, list)) else out)).ravel()[:1]
        except Exception as e:  # pragma: no cover - skip unsupported combos
            results.append({"op": name, "error": str(e)[:120]})
            continue

        def sync(r):
            onp.asarray(jax.device_get(
                r[0] if isinstance(r, (tuple, list)) else r)).ravel()[:1]

        for _ in range(warmup):
            sync(jitted(*arrays))
        t0 = time.perf_counter()
        for _ in range(runs):
            r = jitted(*arrays)
        sync(r)
        jit_ms = (time.perf_counter() - t0) / runs * 1e3

        t0 = time.perf_counter()
        for _ in range(runs):
            r = fn(*arrays)
        sync(r)
        eager_ms = (time.perf_counter() - t0) / runs * 1e3
        results.append({"op": name, "jit_ms": round(jit_ms, 4),
                        "eager_ms": round(eager_ms, 4)})
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="per-op timing harness")
    p.add_argument("--ops", nargs="*", default=None)
    p.add_argument("--runs", type=int, default=10)
    p.add_argument("--large", action="store_true",
                   help="1024^2 operands instead of 128^2")
    p.add_argument("--json", default=None, help="write results to file")
    args = p.parse_args(argv)
    results = run_op_benchmark(args.ops, runs=args.runs, large=args.large)
    ok = [r for r in results if "jit_ms" in r]
    bad = [r for r in results if "error" in r]
    print(f"{'Op':<36}{'jit(ms)':>10}{'eager(ms)':>11}")
    print("-" * 57)
    for r in sorted(ok, key=lambda r: -r["jit_ms"]):
        print(f"{r['op']:<36}{r['jit_ms']:>10.3f}{r['eager_ms']:>11.3f}")
    if bad:
        print(f"\n{len(bad)} ops skipped with errors:")
        for r in bad:
            print(f"  {r['op']}: {r['error']}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
