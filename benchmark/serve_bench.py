#!/usr/bin/env python3
"""Continuous-batching serving benchmark: offered-QPS load generator
against ``mxnet_tpu.serve.DecodeServer``.

Arms (one JSON line each):

- **static_batch8** — the pre-serving baseline: one ``kv_generate``
  batch-8 compiled scan, the repo's measured "~6.5k tok/s batch-8"
  configuration (BASELINE.md "Autoregressive decode").  Aggregate
  tok/s only; a static batch cannot admit mid-flight.
- **saturated** — the slot pool at full occupancy (backlog always
  ≥ pool size): aggregate tok/s and the ratio vs static_batch8.  The
  ISSUE 7 acceptance bar is ratio ≥ 0.8 — the price of serving
  (per-step dispatch + readback + scheduling) measured against the
  single-dispatch offline scan.  The bar holds where decode compute
  dominates (the ``--cpu-full``/TPU geometries); the tiny ``--smoke``
  geometry is dispatch-bound by construction and pins a lower floor.
- **ragged_occ=...** — the SAME ragged workload (per 8-request wave:
  one ``N_max`` request, seven short ones sized so useful tokens are
  25/50/100% of the padded batch) served both ways: static padded
  batches (every lane runs to the batch max, one ``kv_generate`` per
  wave) vs slot-pool continuous batching (retired slots re-admit from
  the queue).  Useful tok/s each; continuous must win at ≤ 50%
  occupancy (ISSUE 7 acceptance — this is the arm
  ``benchmark/decode_bench.py`` re-exports).
- **qps=...** — Poisson arrivals at a fraction of the saturated rate:
  p50/p99 TTFT and inter-token gaps (measured at the host readback),
  aggregate tok/s, occupancy.
- **paged_residency** — the ISSUE 16 acceptance arm: a long-context
  ragged mix (1-in-8 requests at 60% of ``max_length``, chunked in;
  the rest one-page interactive requests) served on a page pool
  priced at a DENSE 2-slot budget.  Columns: peak resident sequences
  vs the dense equivalent at EQUAL KV HBM (``resident_x``, asserted
  >= 2x on every profile), peak pages vs capacity, useful tok/s.
- **kv_quant_residency** — the ISSUE 18 acceptance arm: the same
  uniform 4-page request mix served twice at the SAME ``hbm_budget``,
  f32 pages vs int8 (codes + per-page-scale) pages.  Columns: peak
  resident sequences each way and their ratio (``resident_x``,
  asserted >= 1.9x on the float32-cache profiles), pages/pool bytes
  each way, and ``greedy_agreement`` — the per-stream top-1 agreement
  of the int8 streams against their f32 twins (the PARITY.md
  tolerance; asserted >= 0.9 where the ratio is gated).
- **prefix_hit** — identical-prompt resubmission against the COW
  prefix cache: p50 hit TTFT vs p50 miss TTFT (full prefill) vs p50
  decode-step gap.  Structural pins on every profile: token parity
  with the producer, ``prefix_hits`` == hit count, ZERO admit/chunk
  dispatches across the hit window; full profiles also assert the
  timing bar (hit TTFT ≈ one decode step, not a prefill).
- **ragged_spec** — the ISSUE 17 speculative-decoding arm: the SAME
  ragged workload served with draft-and-verify ON (the other arms pin
  ``spec=False`` — they are the plain-step baseline whose dispatch
  accounting the smoke asserts).  Reports the accept rate and the
  ``tokens_per_dispatch`` multiplier; every profile asserts it
  > 1.5 (the greedy decode of the bench models is self-similar, so
  the n-gram drafter's proposals verify at high acceptance).

Every arm also reports **tokens_per_dispatch** — tokens delivered per
slot-advancing dispatch, ``total_tokens / (total_tokens -
draft_accepted)`` from the stream ledgers: exactly 1.0 on the
non-spec path (one token per lane per dispatch, asserted by the
smoke), > 1 only when speculative verification accepts drafts.

- **admit_sequential / admit_batched / admit_ratio** — the
  admission-heavy workload (ISSUE 8): Poisson-sized bursts of
  SHORT-budget requests land at an idle step boundary, so admission
  dispatch cost dominates.  ``admit_sequential`` pins
  ``admit_sizes=(1,)`` (the per-request admission baseline);
  ``admit_batched`` uses the default bucketed ``(A, P)`` wave ladder —
  k pending prompts at a step boundary cost 1 admit dispatch, not k
  (asserted per burst, both arms, every profile).  Columns: useful
  tok/s, p50/p99 TTFT (the metric batched admission moves),
  ``admit_dispatches_per_request``.

Every arm that serves streams reports p50/p99 TTFT
(``TokenStream.ttft``) next to its throughput.  Full profiles also
record ``MXNET_TELEMETRY_MEM=1`` compile events and attach
``mem_temp_mb`` / ``mem_peak_mb`` columns (XLA ``memory_analysis()``
of the arm's executable) to the measured rows — sized for the row's
``platform``, so CPU-profile numbers are CPU buffer sizes, not TPU
HBM.

``--smoke``: tiny geometry, no TPU — saturated arm with token-stream
parity against ``kv_generate`` asserted, dispatch accounting checked
(1 step dispatch per decode step, 1 admit dispatch per burst),
throughput-ratio floor + the ragged continuous-vs-static-padded win
asserted; the tier-1 gate (tests/test_serve.py shells it).
``--cpu-full`` forces the larger CPU geometry where the 0.8 saturated
bar and the >= 1.3x batched-admission bar are meaningful.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

from benchmark import mem_fields


def emit_row(row):
    """One measured row: stdout JSON line (the BENCH_*.json trajectory
    format) AND the telemetry event stream (kind ``bench``), so a
    ``MXNET_TELEMETRY_JSONL`` recording carries the bench rows next to
    the compile/serve events in one schema
    (``tools/telemetry_report.py`` renders both)."""
    print(json.dumps(row))
    sys.stdout.flush()
    from mxnet_tpu import telemetry
    telemetry.emit("bench", **row)


def phase(name):
    """Arm boundary marker in the event stream (steady-state retrace
    accounting in telemetry_report keys off these)."""
    from mxnet_tpu import telemetry
    telemetry.emit("phase", name=name)


def build_model(profile):
    import mxnet_tpu as mx
    from mxnet_tpu.models import GPT, GPTConfig

    mx.random.seed(0)
    cfg = {
        "smoke": GPTConfig(vocab_size=512, max_length=128, num_layers=2,
                           units=64, num_heads=4, hidden_size=128),
        "cpu": GPTConfig(vocab_size=4096, max_length=256, num_layers=4,
                         units=256, num_heads=8, hidden_size=1024),
        "tpu": GPTConfig(vocab_size=32768, max_length=512,
                         num_layers=12, units=768, num_heads=12,
                         hidden_size=3072, dtype="bfloat16"),
    }[profile]
    net = GPT(cfg)
    net.initialize(mx.init.Normal(0.02))
    return net, cfg


def tokens_per_dispatch(streams):
    """Tokens delivered per slot-advancing dispatch, from the stream
    ledgers: every token batch a stream receives rides one dispatch
    (admit / chunk / step / verify), and a verify batch carries its
    accepted drafts on top of the dispatch's own token — so the
    multiplier is ``total / (total - accepted)``.  Exactly 1.0 when
    nothing was accepted (the non-spec invariant the smoke pins)."""
    total = sum(len(s._toks) for s in streams)
    acc = sum(s.draft_accepted for s in streams)
    return total / max(total - acc, 1)


def static_batch_rate(net, cfg, B, P, N):
    """Offline reference: one compiled batch-B scan, tok/s."""
    from mxnet_tpu.models import kv_generate

    prompt = onp.random.RandomState(0).randint(0, cfg.vocab_size,
                                               (B, P))
    kv_generate(net, prompt, max_new_tokens=N, temperature=0.0)  # warm
    t0 = time.perf_counter()
    kv_generate(net, prompt, max_new_tokens=N, temperature=0.0)
    dt = time.perf_counter() - t0
    return B * N / dt


def warm_server(srv, cfg, P):
    """Compile the step and every (A, P-bucket) admission program the
    run will hit, off the clock: one pump-driven burst per pinned wave
    size, then reset the dispatch counters."""
    rng = onp.random.RandomState(99)
    S = srv.stats()["num_slots"]
    for a in srv.admit_sizes:
        if a > S:
            break
        ws = [srv.submit(rng.randint(0, cfg.vocab_size, (P,)),
                         max_new_tokens=2) for _ in range(a)]
        while srv.pump():
            pass
        for w in ws:
            w.tokens(60)
    srv.reset_counters()


def run_saturated(net, cfg, S, P, N, n_requests):
    """Pool at full occupancy, pump-driven: (tok/s, streams, server)."""
    from mxnet_tpu.serve import DecodeServer

    rng = onp.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (P,))
               for _ in range(n_requests)]
    # spec=False: this arm is the plain-step baseline — the smoke pins
    # its dispatch accounting AND tokens_per_dispatch == 1.0
    srv = DecodeServer(net, max_total_len=P + N, pool_sizes=(S,),
                       spec=False, autostart=False)
    warm_server(srv, cfg, P)

    t0 = time.perf_counter()
    streams = [srv.submit(p, max_new_tokens=N) for p in prompts]
    while srv.pump():
        pass
    wall = time.perf_counter() - t0
    toks = sum(len(s.tokens(1)) for s in streams)
    return toks / wall, prompts, streams, srv


def ragged_lengths(S, N_max, frac, n_requests):
    """Per wave of ``S``: one ``N_max`` request (it sets the padded
    batch length) and ``S - 1`` short ones sized so the wave's useful
    tokens are ``frac`` of the ``S * N_max`` padded budget."""
    if S == 1:
        # a 1-slot pool has no short lanes — every wave is the one
        # full-length request (occupancy is 1.0 by construction)
        return [N_max] * n_requests
    short = max(1, round((frac * S * N_max - N_max) / (S - 1)))
    short = min(short, N_max)
    return [N_max if i % S == 0 else short for i in range(n_requests)]


def run_ragged(net, cfg, S, P, N_max, frac, n_requests):
    """One ragged workload, served both ways.

    Returns ``(static_tps, cont_tps, occupancy, ttfts)`` — USEFUL
    tokens/sec (requested continuation tokens only; the static padded
    batch also decodes ``N_max - len_i`` wasted tail tokens per lane,
    which is exactly the cost continuous batching exists to avoid) and
    the continuous arm's per-request TTFTs."""
    from mxnet_tpu.models import kv_generate
    from mxnet_tpu.serve import DecodeServer

    lens = ragged_lengths(S, N_max, frac, n_requests)
    rng = onp.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (P,))
               for _ in range(n_requests)]
    useful = sum(lens)

    # -- static padded batches: every wave runs to its longest request
    batch = onp.stack(prompts[:S])
    kv_generate(net, batch, max_new_tokens=N_max, temperature=0.0)
    t0 = time.perf_counter()
    for i in range(0, n_requests, S):
        chunk = onp.stack(prompts[i:i + S])
        n_batch = max(lens[i:i + S])
        kv_generate(net, chunk, max_new_tokens=n_batch, temperature=0.0)
    static_tps = useful / (time.perf_counter() - t0)

    # -- continuous batching: retired slots back-fill from the queue
    # (plain-step baseline; the ragged_spec arm is the speculative one)
    srv = DecodeServer(net, max_total_len=P + N_max, pool_sizes=(S,),
                       spec=False, autostart=False)
    warm_server(srv, cfg, P)
    t0 = time.perf_counter()
    streams = [srv.submit(p, max_new_tokens=n)
               for p, n in zip(prompts, lens)]
    while srv.pump():
        pass
    cont_tps = sum(len(s.tokens(1)) for s in streams) / \
        (time.perf_counter() - t0)
    occ = srv.stats()["occupancy"]
    ttfts = [s.ttft for s in streams]
    srv.close()
    return static_tps, cont_tps, occ, ttfts


def run_ragged_spec(net, cfg, S, P, N_max, frac, n_requests):
    """The ragged workload with speculative draft-and-verify ON
    (ISSUE 17): the same ragged length DISTRIBUTION as ``run_ragged``'s
    continuous arm at 4x the generation budget, served with the default
    n-gram drafter.  Returns ``(tok/s, tokens_per_dispatch,
    accept_rate, step+verify dispatch counts, (prompts, lens,
    streams))``.  The n-gram drafter needs a few emitted tokens before
    the stream's self-similarity gives it material (a slot's first
    decode is always a plain step — the ramp), so the arm generates
    long enough for acceptance to amortise that ramp; the bench models'
    greedy decode is self-similar and the multiplier clears the > 1.5
    acceptance bar."""
    from mxnet_tpu.serve import DecodeServer

    N_max = 4 * N_max
    lens = ragged_lengths(S, N_max, frac, n_requests)
    rng = onp.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (P,))
               for _ in range(n_requests)]
    srv = DecodeServer(net, max_total_len=P + N_max, pool_sizes=(S,),
                       spec=True, autostart=False)
    warm_server(srv, cfg, P)
    t0 = time.perf_counter()
    streams = [srv.submit(p, max_new_tokens=n)
               for p, n in zip(prompts, lens)]
    while srv.pump():
        pass
    tps = sum(len(s.tokens(1)) for s in streams) / \
        (time.perf_counter() - t0)
    st = srv.stats()
    tpd = tokens_per_dispatch(streams)
    dispatches = (srv.counters["step_dispatches"],
                  srv.counters["verify_dispatches"])
    srv.close()
    return tps, tpd, st["draft_accept_rate"], dispatches, \
        (prompts, lens, streams)


def run_paged_residency(net, cfg, n_requests):
    """ISSUE 16 acceptance arm: a long-context ragged mix on a page
    pool priced at a DENSE ``S_dense``-slot budget.  A dense slot pool
    reserves ``max_total_len`` of K/V per resident sequence, so that
    HBM buys exactly ``S_dense`` lanes; the paged pool spends the same
    bytes on fixed-size pages and keeps every lane whose LIVE tokens
    fit — peak resident sequences is the metric.  Long prompts stream
    in via chunked prefill (buckets pinned small on purpose)."""
    from mxnet_tpu.serve import DecodeServer
    from mxnet_tpu.serve.engine import pool_state_bytes

    T = cfg.max_length
    page = 16
    maxp = -(-T // page)
    S_dense = 2                    # what the page budget buys densely
    num_pages = S_dense * maxp     # EQUAL KV HBM by construction
    S = 4 * S_dense                # lanes offered on that same budget
    srv = DecodeServer(net, max_total_len=T, pool_sizes=(S,),
                       page_size=page, num_pages=num_pages,
                       prefill_buckets=(8, 32), prefix_cache=False,
                       spec=False, autostart=False)
    rng = onp.random.RandomState(11)
    reqs = []
    for i in range(n_requests):
        if i % 8 == 0:   # 1-in-8 long-context request — chunks in
            reqs.append((rng.randint(0, cfg.vocab_size,
                                     (int(T * 0.6),)), 8))
        else:            # short interactive request: one live page
            reqs.append((rng.randint(0, cfg.vocab_size, (8,)), 8))
    t0 = time.perf_counter()
    streams = [srv.submit(p, max_new_tokens=n) for p, n in reqs]
    peak_res = peak_pages = 0
    while srv.pump():
        st = srv.stats()
        peak_res = max(peak_res, st["in_flight"])
        peak_pages = max(peak_pages, st["pages_in_use"])
    wall = time.perf_counter() - t0
    toks = sum(len(s.tokens(1)) for s in streams)
    paged_bytes = srv.stats()["pool_bytes"]
    dense_bytes = pool_state_bytes(srv._progs, S_dense,
                                   num_pages=num_pages)
    counters = dict(srv.counters)
    # parity spot-check: one long (chunked) + three short streams
    from mxnet_tpu.models import kv_generate
    for (p, n), s in list(zip(reqs, streams))[:4]:
        ref = list(kv_generate(net, p[None], max_new_tokens=n,
                               temperature=0.0)[0, p.size:])
        assert s.tokens(1) == ref, "paged ragged stream != kv_generate"
    srv.close()
    return {"peak_resident": peak_res, "dense_resident": S_dense,
            "resident_x": peak_res / S_dense, "pages_total": num_pages,
            "peak_pages": peak_pages, "paged_pool_bytes": paged_bytes,
            "dense_pool_bytes": dense_bytes,
            "tokens_per_sec": toks / wall, "counters": counters}


def run_kv_quant_residency(net, cfg, n_requests):
    """ISSUE 18 acceptance arm: the SAME uniform long-ish mix under the
    SAME ``hbm_budget``, f32 pages vs int8 (codes + per-page-scale)
    pages.  The budget prices the f32 pool exactly; the int8 pool
    spends the identical pool bytes on ~4x as many pages (float32
    cache dtype; ~2x under bf16), so its peak resident sequences clear
    ~2x the f32 pool's.  Requests are sized at 4 pages each so
    residency is pages-bound on the f32 side and lane-bound on the
    int8 side; prompts overflow the largest prefill bucket on purpose
    so chunked prefill runs against the quantized pool.  Parity is the
    PARITY.md tolerance: per-stream greedy top-1 agreement of every
    int8 stream against its f32 twin."""
    from mxnet_tpu.serve import DecodeServer
    from mxnet_tpu.serve.engine import (PoolPrograms,
                                        admit_scratch_bytes,
                                        pool_state_bytes)

    T = cfg.max_length
    page = 16
    S = 8
    pages_f32 = 16                 # 4 requests' worth of f32 pages
    prompt_len = 3 * page + page // 2   # 3.5 pages -> chunks at C=32
    N = page // 2                  # total 4*page: exactly 4 pages
    # price both pools off throwaway program sets (no executables are
    # traced until a server pumps), then hand BOTH servers the same
    # budget: the f32 pool fills it; the int8 pool converts it to pages
    probe = PoolPrograms(net, num_slots=S, max_total=T,
                         page_size=page, num_pages=1)
    fixed = pool_state_bytes(probe, S, num_pages=1) - probe.page_bytes()
    pool_f32 = fixed + pages_f32 * probe.page_bytes()
    probe_i8 = PoolPrograms(net, num_slots=S, max_total=T,
                            page_size=page, num_pages=1,
                            kv_dtype="int8")
    fixed_i8 = pool_state_bytes(probe_i8, S, num_pages=1) \
        - probe_i8.page_bytes()
    pages_i8 = (pool_f32 - fixed_i8) // probe_i8.page_bytes()
    budget = pool_f32 + admit_scratch_bytes(probe, S)

    rng = onp.random.RandomState(17)
    reqs = [rng.randint(0, cfg.vocab_size, (prompt_len,))
            for _ in range(n_requests)]
    out = {}
    for dtype, num_pages in (("native", pages_f32), ("int8", pages_i8)):
        srv = DecodeServer(net, max_total_len=T, pool_sizes=(S,),
                           page_size=page, num_pages=num_pages,
                           prefill_buckets=(8, 32), prefix_cache=False,
                           spec=False, hbm_budget=budget,
                           kv_dtype=dtype, autostart=False)
        assert srv.stats()["pool_bytes"] <= pool_f32, \
            (dtype, srv.stats()["pool_bytes"], pool_f32)
        t0 = time.perf_counter()
        streams = [srv.submit(p, max_new_tokens=N) for p in reqs]
        peak = 0
        while srv.pump():
            peak = max(peak, srv.stats()["in_flight"])
        wall = time.perf_counter() - t0
        toks = [s.tokens(1) for s in streams]
        assert all(len(t) == N for t in toks)
        out[dtype] = {"peak": peak, "toks": toks,
                      "pool_bytes": srv.stats()["pool_bytes"],
                      "tokens_per_sec": sum(map(len, toks)) / wall,
                      "counters": dict(srv.counters)}
        srv.close()
    agree = onp.mean([onp.mean([a == b for a, b in zip(f, q)])
                      for f, q in zip(out["native"]["toks"],
                                      out["int8"]["toks"])])
    return {"budget": budget, "pages_f32": pages_f32,
            "pages_int8": int(pages_i8),
            "peak_resident_f32": out["native"]["peak"],
            "peak_resident_int8": out["int8"]["peak"],
            "resident_x": out["int8"]["peak"] / out["native"]["peak"],
            "pool_bytes_f32": out["native"]["pool_bytes"],
            "pool_bytes_int8": out["int8"]["pool_bytes"],
            "greedy_agreement": float(agree),
            "tokens_per_sec_int8": out["int8"]["tokens_per_sec"],
            "chunk_dispatches_int8":
                out["int8"]["counters"].get("chunk_dispatches", 0)}


def run_prefix_hits(net, cfg, S, P, N, n_hits):
    """ISSUE 16 prefix-cache arm: misses (distinct prompts, full
    prefill each) vs hits (the same prompt resubmitted after its
    producer retired).  A hit admits by mapping the cached pages —
    zero prefill dispatches — so its TTFT is one decode step.  Each
    request is served alone (pump-driven, sequential) so every TTFT
    sample is clean of queueing."""
    from mxnet_tpu.serve import DecodeServer

    srv = DecodeServer(net, max_total_len=P + N, pool_sizes=(S,),
                       spec=False, autostart=False)
    warm_server(srv, cfg, P)
    rng = onp.random.RandomState(13)
    shared = rng.randint(0, cfg.vocab_size, (P,))

    miss_ttfts = []
    for _ in range(3):
        s = srv.submit(rng.randint(0, cfg.vocab_size, (P,)),
                       max_new_tokens=N)
        while srv.pump():
            pass
        s.tokens(60)
        miss_ttfts.append(s.ttft)
    cold = srv.submit(shared, max_new_tokens=N)   # registers the pages
    while srv.pump():
        pass
    ref = cold.tokens(60)
    gaps = [b - a for a, b in zip(cold.times, cold.times[1:])]

    srv.reset_counters()
    hits = []
    for _ in range(n_hits):
        s = srv.submit(shared, max_new_tokens=N)
        while srv.pump():
            pass
        hits.append(s)
    hit_ttfts = [s.ttft for s in hits]
    counters = dict(srv.counters)
    parity = all(s.tokens(60) == ref for s in hits)
    srv.close()
    return hit_ttfts, miss_ttfts, gaps, counters, parity


def run_qps(net, cfg, S, P, N, qps, n_requests, seed=2):
    """Poisson arrivals against the background-thread server; returns
    (tok/s, ttft list (s), inter-token gap list (s), occupancy)."""
    from mxnet_tpu.serve import DecodeServer

    rng = onp.random.RandomState(seed)
    py_rng = random.Random(seed)
    srv = DecodeServer(net, max_total_len=P + N, pool_sizes=(S,),
                       spec=False, autostart=False)
    warm_server(srv, cfg, P)        # pump-driven warm, then hand off
    srv.start()

    streams = []
    t0 = time.perf_counter()
    for _ in range(n_requests):
        streams.append(srv.submit(
            rng.randint(0, cfg.vocab_size, (P,)), max_new_tokens=N))
        time.sleep(py_rng.expovariate(qps))
    toks = sum(len(s.tokens(120)) for s in streams)
    wall = time.perf_counter() - t0
    ttfts = [s.ttft for s in streams]
    gaps = []
    for s in streams:
        gaps.extend(b - a for a, b in zip(s.times, s.times[1:]))
    occ = srv.stats()["occupancy"]
    srv.close()
    return toks / wall, ttfts, gaps, occ


def run_admission(net, cfg, S, P, N, n_bursts, sequential, seed=7):
    """Admission-heavy arm: Poisson-sized bursts of short-budget
    requests land at an idle step boundary, so admission dispatch cost
    dominates the serve.  ``sequential=True`` pins ``admit_sizes=(1,)``
    — the per-request admission baseline the batched ``(A, P)`` wave
    dispatch replaces; both arms see the identical workload (same
    seed -> same burst sizes and prompts).

    Returns ``(tok/s, ttfts, admit_dispatches_per_request,
    [(burst_k, admit_dispatches)])``."""
    from mxnet_tpu.serve import DecodeServer

    rng = onp.random.RandomState(seed)
    srv = DecodeServer(net, max_total_len=P + N, pool_sizes=(S,),
                       admit_sizes=(1,) if sequential else None,
                       spec=False, autostart=False)
    warm_server(srv, cfg, P)
    streams, bursts = [], []
    t0 = time.perf_counter()
    for _ in range(n_bursts):
        k = int(min(S, max(1, rng.poisson(S))))
        before = srv.counters["admit_dispatches"]
        streams += [srv.submit(rng.randint(0, cfg.vocab_size, (P,)),
                               max_new_tokens=N) for _ in range(k)]
        while srv.pump():
            pass
        bursts.append((k, srv.counters["admit_dispatches"] - before))
    wall = time.perf_counter() - t0
    toks = sum(len(s.tokens(1)) for s in streams)
    ttfts = [s.ttft for s in streams]
    apr = srv.counters["admit_dispatches"] / len(streams)
    srv.close()
    return toks / wall, ttfts, apr, bursts


def _pct(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny saturated + ragged arms: kv_generate "
                         "parity, dispatch accounting, throughput "
                         "floors (tier-1 gate, CPU)")
    ap.add_argument("--cpu-full", action="store_true",
                    help="larger CPU geometry (compute-bound: the "
                         "0.8 saturated bar applies)")
    args = ap.parse_args()

    if not args.smoke:
        # memory columns for the measured rows: compile events carry
        # memory_analysis fields (one extra AOT compile per program —
        # warm-up cost only, off the measured clock; the smoke skips it
        # to stay inside the tier-1 time budget)
        os.environ.setdefault("MXNET_TELEMETRY_MEM", "1")

    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu" and not args.smoke
    profile = "tpu" if on_tpu else ("smoke" if args.smoke else "cpu")
    net, cfg = build_model(profile)
    S, P = 8, 16
    N = {"tpu": 128, "cpu": 32, "smoke": 12}[profile]
    n_requests = {"tpu": 32, "cpu": 16, "smoke": 16}[profile]

    phase("static_batch8")
    static_rate = static_batch_rate(net, cfg, S, P, N)
    emit_row({"bench": "serve", "mode": "static_batch8",
              "profile": profile,
              "tokens_per_sec": round(static_rate, 1),
              "tokens_per_dispatch": 1.0,   # scan: 1 token/lane/step
              "batch": S, "new_tokens": N,
              "platform": platform,
              **mem_fields("models.kv_generate")})

    phase("saturated")
    rate, prompts, streams, srv = run_saturated(net, cfg, S, P, N,
                                                n_requests)
    stats = srv.stats()
    ratio = rate / static_rate
    steps = srv.counters["step_dispatches"]
    admits = srv.counters["admit_dispatches"]
    sat_ttfts = [s.ttft for s in streams]
    sat_tpd = tokens_per_dispatch(streams)
    emit_row({"bench": "serve", "mode": "saturated",
              "profile": profile,
              "tokens_per_sec": round(rate, 1),
              "tokens_per_dispatch": round(sat_tpd, 3),
              "vs_static_batch8": round(ratio, 3),
              "occupancy": round(stats["occupancy"], 3),
              "p50_ttft_ms": round(_pct(sat_ttfts, 0.5) * 1e3, 3),
              "p99_ttft_ms": round(_pct(sat_ttfts, 0.99) * 1e3, 3),
              "num_slots": S, "requests": n_requests,
              "new_tokens": N, "step_dispatches": steps,
              "admit_dispatches": admits,
              "pool_bytes": stats["pool_bytes"],
              "platform": platform,
              **mem_fields("serve.step", srv.telemetry_label)})

    if args.smoke:
        # parity: every served stream reproduces the offline decode
        from mxnet_tpu.models import kv_generate
        for p, s in zip(prompts, streams):
            ref = list(kv_generate(net, p[None], max_new_tokens=N,
                                   temperature=0.0)[0, P:])
            assert s.tokens(1) == ref, "served stream != kv_generate"
        # dispatch accounting: decode steps are single-dispatch, and
        # the n_requests backlog admits in ceil(n / S) batched waves,
        # not one dispatch per request
        waves = -(-n_requests // S)
        assert waves <= admits <= waves + 1, (admits, waves)
        floor = (n_requests * (N - 1)) // S
        assert steps >= floor, (steps, floor)
        assert steps <= floor + n_requests + 4, (steps, floor)
        # the non-spec path delivers EXACTLY one token per lane per
        # dispatch — the ISSUE 17 regression gate on the baseline
        assert sat_tpd == 1.0, sat_tpd
        assert srv.counters["verify_dispatches"] == 0
        # ISSUE 9 telemetry invariants, from the registry/event stream
        # alone: warm_server compiled the whole usable (A, P) admission
        # ladder (every pinned A ≤ pool size × the single 16-token
        # prompt bucket) and ONE step program; the measured run added
        # ZERO compiles (steady state, no retraces); step dispatches in
        # the registry == decode steps (1 executable dispatch/step).
        from mxnet_tpu import telemetry
        if telemetry.telemetry_enabled():
            label = srv.telemetry_label
            adm_comp = [e for e in telemetry.events("compile")
                        if e.get("site") == "serve.admit"
                        and e.get("server") == label]
            pairs = {(e["pool"], e["a_bucket"], e["p_bucket"])
                     for e in adm_comp}
            ladder = len([a for a in srv.admit_sizes if a <= S])
            assert len(adm_comp) == ladder == len(pairs), \
                (ladder, adm_comp)
            assert ladder <= (len(srv.admit_sizes)
                              * len(srv.prefill_buckets)
                              * len(srv.pool_sizes))
            step_comp = [e for e in telemetry.events("compile")
                         if e.get("site") == "serve.step"
                         and e.get("server") == label]
            assert len(step_comp) == 1, step_comp
            assert not any(e.get("retrace")
                           for e in adm_comp + step_comp)
            reg_steps = telemetry.counter(
                "serve_step_dispatches_total", server=label).value
            assert reg_steps == steps == srv.stats()["steps"], \
                (reg_steps, steps)
            print("# telemetry OK: admission-ladder compiles "
                  f"{len(adm_comp)}, 1 step compile, 0 retraces, "
                  f"{reg_steps} step dispatches == steps")
    srv.close()

    ragged = {}
    for frac in (0.25, 0.5, 1.0):
        phase(f"ragged_occ={frac}")
        st, ct, occ, rt = run_ragged(net, cfg, S, P, N, frac,
                                     n_requests)
        ragged[frac] = (st, ct)
        emit_row({"bench": "serve",
                  "mode": f"ragged_occ={frac}",
                  "profile": profile,
                  "static_padded_tok_s": round(st, 1),
                  "continuous_tok_s": round(ct, 1),
                  "continuous_vs_static": round(ct / st, 3),
                  "tokens_per_dispatch": 1.0,   # spec=False baseline
                  "occupancy": round(occ, 3),
                  "p50_ttft_ms": round(_pct(rt, 0.5) * 1e3, 3),
                  "p99_ttft_ms": round(_pct(rt, 0.99) * 1e3, 3),
                  "platform": platform})

    # speculative-decoding arm (ISSUE 17): the ragged workload with
    # draft-and-verify ON — the accept rate and the tokens_per_dispatch
    # multiplier are the columns; > 1.5 is the acceptance bar (every
    # profile: acceptance is a property of the greedy stream's
    # self-similarity, not of dispatch cost)
    phase("ragged_spec")
    sp_tps, sp_tpd, sp_acc, (sp_steps, sp_verifies), sp_work = \
        run_ragged_spec(net, cfg, S, P, N, 0.5, n_requests)
    emit_row({"bench": "serve", "mode": "ragged_spec",
              "profile": profile,
              "tokens_per_sec": round(sp_tps, 1),
              "tokens_per_dispatch": round(sp_tpd, 3),
              "accept_rate": round(sp_acc, 3),
              "step_dispatches": sp_steps,
              "verify_dispatches": sp_verifies,
              "vs_plain_continuous": round(sp_tps / ragged[0.5][1], 3),
              "platform": platform})
    assert sp_verifies > 0, "spec arm never dispatched a verify"
    assert sp_tpd > 1.5, \
        f"ragged spec tokens/dispatch {sp_tpd:.2f} <= 1.5"
    if args.smoke:
        # speculation must not change a single token: spot-check the
        # spec arm's streams against the offline greedy decode
        from mxnet_tpu.models import kv_generate
        sp_prompts, sp_lens, sp_streams = sp_work
        for p, n, s in list(zip(sp_prompts, sp_lens, sp_streams))[:4]:
            ref = list(kv_generate(net, p[None], max_new_tokens=n,
                                   temperature=0.0)[0, p.size:])
            assert s.tokens(1) == ref, "spec stream != kv_generate"

    # paged-residency arm (ISSUE 16): long-context ragged mix on a
    # page pool priced at a dense 2-slot budget — the acceptance bar
    # is >= 2x resident sequences at EQUAL KV HBM (every profile; the
    # memory_report --hbm verdict prices the same accountant bytes)
    phase("paged_residency")
    n_res = {"tpu": 32, "cpu": 16, "smoke": 24}[profile]
    res = run_paged_residency(net, cfg, n_res)
    emit_row({"bench": "serve", "mode": "paged_residency",
              "profile": profile,
              "peak_resident": res["peak_resident"],
              "dense_resident": res["dense_resident"],
              "resident_x": round(res["resident_x"], 2),
              "pages_total": res["pages_total"],
              "peak_pages": res["peak_pages"],
              "paged_pool_bytes": res["paged_pool_bytes"],
              "dense_pool_bytes": res["dense_pool_bytes"],
              "tokens_per_sec": round(res["tokens_per_sec"], 1),
              "tokens_per_dispatch": 1.0,   # spec=False baseline
              "chunk_dispatches": res["counters"]["chunk_dispatches"],
              "platform": platform})
    assert res["resident_x"] >= 2.0, \
        (f"paged residency {res['resident_x']:.2f}x < 2x dense at "
         f"equal HBM")
    assert res["peak_pages"] <= res["pages_total"], res
    assert res["counters"]["chunk_dispatches"] > 0, \
        "long-context mix never exercised chunked prefill"

    # kv-quant residency arm (ISSUE 18): the same mix at the SAME
    # hbm_budget, f32 vs int8 pages — the capacity win of quantized
    # pages measured as peak resident sequences, priced by the same
    # accountant bytes memory_report --hbm verdicts against
    phase("kv_quant_residency")
    n_kvq = {"tpu": 24, "cpu": 12, "smoke": 12}[profile]
    kvq = run_kv_quant_residency(net, cfg, n_kvq)
    emit_row({"bench": "serve", "mode": "kv_quant_residency",
              "profile": profile,
              "hbm_budget": kvq["budget"],
              "pages_f32": kvq["pages_f32"],
              "pages_int8": kvq["pages_int8"],
              "peak_resident_f32": kvq["peak_resident_f32"],
              "peak_resident_int8": kvq["peak_resident_int8"],
              "resident_x": round(kvq["resident_x"], 2),
              "pool_bytes_f32": kvq["pool_bytes_f32"],
              "pool_bytes_int8": kvq["pool_bytes_int8"],
              "greedy_agreement": round(kvq["greedy_agreement"], 4),
              "tokens_per_sec": round(kvq["tokens_per_sec_int8"], 1),
              "tokens_per_dispatch": 1.0,   # spec=False baseline
              "chunk_dispatches": kvq["chunk_dispatches_int8"],
              "platform": platform})
    # structural pins, every profile: the int8 pool never exceeds the
    # f32 pool's bytes, and the long prompts chunked in quantized
    assert kvq["pool_bytes_int8"] <= kvq["pool_bytes_f32"], kvq
    assert kvq["chunk_dispatches_int8"] > 0, \
        "kv-quant mix never exercised chunked prefill on the int8 pool"
    if args.smoke or profile == "cpu":
        # the ISSUE 18 acceptance bar (float32 cache dtype: int8 pages
        # are ~4x smaller, residency is lane-capped at 2x the f32
        # peak); bf16 profiles report the honest ~2x-bytes column
        # without the gate
        assert kvq["resident_x"] >= 1.9, kvq
        assert kvq["greedy_agreement"] >= 0.9, kvq

    # prefix-hit TTFT arm (ISSUE 16): identical-prompt resubmission
    # admits from the prefix cache — zero prefill dispatches, first
    # token after ONE decode step
    phase("prefix_hit")
    n_hits = 4
    hit_ttfts, miss_ttfts, gaps, pc, parity = run_prefix_hits(
        net, cfg, S, 64, N, n_hits)
    hit_p50 = _pct(hit_ttfts, 0.5)
    miss_p50 = _pct(miss_ttfts, 0.5)
    gap_p50 = _pct(gaps, 0.5)
    emit_row({"bench": "serve", "mode": "prefix_hit",
              "profile": profile,
              "tokens_per_dispatch": 1.0,   # spec=False baseline
              "p50_hit_ttft_ms": round(hit_p50 * 1e3, 3),
              "p50_miss_ttft_ms": round(miss_p50 * 1e3, 3),
              "p50_step_ms": round(gap_p50 * 1e3, 3),
              "hit_ttft_vs_step": round(hit_p50 / max(gap_p50, 1e-9),
                                        3),
              "prefix_hits": pc["prefix_hits"],
              "cow_copies": pc["cow_copies"],
              "admit_dispatches_on_hits": pc["admit_dispatches"],
              "chunk_dispatches_on_hits": pc["chunk_dispatches"],
              "platform": platform})
    # structural pins, every profile: parity, hit/miss counters, and
    # ZERO prefill dispatches across the whole hit window
    assert parity, "prefix-hit stream != its producer's tokens"
    assert pc["prefix_hits"] == n_hits, pc
    assert pc["admit_dispatches"] == 0, pc
    assert pc["chunk_dispatches"] == 0, pc
    assert pc["step_dispatches"] >= n_hits * (N - 1), pc
    if not args.smoke:
        # timing bar where compute dominates dispatch: a hit's first
        # token costs about one decode step, not a prefill
        assert hit_p50 <= max(3 * gap_p50, miss_p50), \
            (hit_p50, gap_p50, miss_p50)

    # admission-heavy arms (ISSUE 8): short decode budgets, Poisson
    # bursts at idle step boundaries — sequential (admit_sizes=(1,),
    # the per-request baseline) vs batched (one (A, P) dispatch per
    # wave).  Identical workload in both arms.
    N_adm = 4
    n_bursts = {"tpu": 8, "cpu": 6, "smoke": 4}[profile]
    adm = {}
    for name, sequential in (("sequential", True), ("batched", False)):
        phase(f"admit_{name}")
        tps, ttfts, apr, bursts = run_admission(net, cfg, S, P, N_adm,
                                                n_bursts, sequential)
        adm[name] = (tps, ttfts, apr, bursts)
        emit_row({
            "bench": "serve", "mode": f"admit_{name}",
            "profile": profile,
            "tokens_per_sec": round(tps, 1),
            "tokens_per_dispatch": 1.0,   # spec=False baseline
            "p50_ttft_ms": round(_pct(ttfts, 0.5) * 1e3, 3),
            "p99_ttft_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
            "admit_dispatches_per_request": round(apr, 3),
            "bursts": [list(b) for b in bursts],
            "new_tokens": N_adm,
            "platform": platform,
            **mem_fields("serve.admit")})
    tps_x = adm["batched"][0] / adm["sequential"][0]
    p99_x = _pct(adm["sequential"][1], 0.99) / \
        max(_pct(adm["batched"][1], 0.99), 1e-9)
    emit_row({"bench": "serve", "mode": "admit_ratio",
              "profile": profile,
              "batched_vs_sequential_tok_s": round(tps_x, 3),
              "batched_p99_ttft_speedup": round(p99_x, 3),
              "platform": platform})
    # k pending prompts at a step boundary cost 1 admit dispatch in
    # the batched arm — and k in the sequential baseline (every
    # profile, tier-1 via --smoke)
    assert all(d == 1 for k, d in adm["batched"][3]), adm["batched"][3]
    assert all(d == k for k, d in adm["sequential"][3]), \
        adm["sequential"][3]
    if not args.smoke:
        # the ISSUE 8 acceptance bar, where compute dominates dispatch
        assert tps_x >= 1.3 or p99_x >= 1.3, (tps_x, p99_x)

    if args.smoke:
        # the tiny geometry is dispatch-bound by construction (a padded
        # batch-8 scan step costs the same as a pool step, so wasted
        # tail tokens are nearly free and the per-step dispatch price
        # dominates): the smoke pins parity, dispatch accounting and a
        # throughput floor, and PRINTS the ragged rows; the acceptance
        # bars (saturated >= 0.8x, ragged continuous win at <= 50%
        # occupancy) are asserted by the compute-bound --cpu-full / TPU
        # profiles and recorded in BASELINE.md.
        # canary floor: the committed-state retrace regression this PR
        # fixed measured 0.04x; honest dispatch-bound runs on a noisy
        # 2-core host land 0.2-0.45x
        assert ratio >= 0.12, f"saturated ratio {ratio:.3f} < 0.12 floor"
        st, ct = ragged[0.25]
        emit_row({"bench": "serve_smoke",
                  "saturated_ratio": round(ratio, 3),
                  "ragged_25_continuous_vs_static":
                      round(ct / st, 3),
                  "admit_batched_vs_sequential":
                      round(tps_x, 3),
                  "admit_p99_ttft_speedup": round(p99_x, 3),
                  "step_dispatches": steps,
                  "paged_resident_x": round(res["resident_x"], 2),
                  "kv_quant_resident_x": round(kvq["resident_x"], 2),
                  "kv_quant_greedy_agreement":
                      round(kvq["greedy_agreement"], 4),
                  "prefix_hit_ttft_vs_step":
                      round(hit_p50 / max(gap_p50, 1e-9), 3),
                  "platform": platform})
        print(f"# serve OK: parity x{n_requests}, {steps} step "
              f"dispatches, saturated {ratio:.2f}x static, "
              f"ragged@25% continuous {ct / st:.2f}x padded, "
              f"batched admission {tps_x:.2f}x tok/s / "
              f"{p99_x:.2f}x p99 TTFT vs per-request, "
              f"paged residency {res['resident_x']:.1f}x dense at "
              f"equal HBM, int8 pages {kvq['resident_x']:.1f}x f32 "
              f"residency at equal HBM "
              f"({kvq['greedy_agreement']:.0%} greedy agreement), "
              f"prefix hits {pc['prefix_hits']} with 0 "
              f"prefill dispatches "
              f"(dispatch-bound toy geometry)")
        return 0

    # acceptance bars — meaningful where decode compute dominates
    assert ratio >= 0.8, \
        f"saturated serving {ratio:.3f}x < 0.8x static batch-8"
    for frac in (0.25, 0.5):
        st, ct = ragged[frac]
        assert ct > st, (f"ragged occ={frac}: continuous {ct:.0f} <= "
                         f"static padded {st:.0f} tok/s")

    # offered-QPS sweep: fractions of the saturated request rate
    sat_req_rate = rate / N
    for frac in (0.25, 0.5, 0.9):
        phase(f"qps_{frac}")
        qps = max(sat_req_rate * frac, 1e-3)
        tps, ttfts, gaps, occ = run_qps(net, cfg, S, P, N, qps,
                                        n_requests)
        emit_row({
            "bench": "serve", "mode": f"qps_{frac}",
            "profile": profile,
            "offered_qps": round(qps, 3),
            "tokens_per_sec": round(tps, 1),
            "tokens_per_dispatch": 1.0,   # spec=False baseline
            "p50_ttft_ms": round(_pct(ttfts, 0.5) * 1e3, 3),
            "p99_ttft_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
            "p50_token_latency_ms": round(_pct(gaps, 0.5) * 1e3, 3),
            "p99_token_latency_ms": round(_pct(gaps, 0.99) * 1e3, 3),
            "occupancy": round(occ, 3),
            "platform": platform})
    return 0


if __name__ == "__main__":
    sys.exit(main())
