"""Shared benchmark helpers.

``mem_fields`` is the one implementation of the "memory columns from
the newest ``mem_*``-carrying compile event" lookup both
``serve_bench.py`` and ``step_profile.py`` attach to their measured
rows — one place to keep the field names / MB rounding in sync.
"""


def mem_fields(site, server=None):
    """Peak/temp memory columns for a measured row, sourced from the
    newest compile event of ``site`` that carries the
    ``MXNET_TELEMETRY_MEM=1`` analysis (optionally filtered to one
    server's label).  Empty when none was recorded.  The numbers are
    buffer sizes on the platform the compile ran on — a CPU-profile
    row reports CPU bytes, not TPU HBM; rows label that via their
    ``platform`` field."""
    from mxnet_tpu import telemetry

    for e in reversed(telemetry.events("compile")):
        if e.get("site") != site:
            continue
        if server is not None and e.get("server") != server:
            continue
        if "mem_peak_bytes" in e:
            return {
                "mem_temp_mb": round(e.get("mem_temp_bytes", 0)
                                     / 2 ** 20, 3),
                "mem_peak_mb": round(e["mem_peak_bytes"] / 2 ** 20, 3),
            }
    return {}
