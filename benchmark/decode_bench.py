#!/usr/bin/env python3
"""Autoregressive decode throughput: KV-cache (one compiled scan) vs the
full-recompute ``GPT.generate`` loop.  Prints one JSON line per mode."""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.models import GPT, GPTConfig, kv_generate

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    mx.random.seed(0)
    cfg = GPTConfig(vocab_size=32768, max_length=1024, num_layers=12,
                    units=768, num_heads=12, hidden_size=3072,
                    dtype="bfloat16" if on_tpu else "float32") \
        if on_tpu else GPTConfig(vocab_size=512, max_length=128,
                                 num_layers=2, units=64, num_heads=4,
                                 hidden_size=128)
    net = GPT(cfg)
    net.initialize(mx.init.Normal(0.02))
    B, P, N = (8, 32, 256) if on_tpu else (2, 8, 16)
    prompt = onp.random.RandomState(0).randint(0, cfg.vocab_size, (B, P))

    # KV-cache path: one compiled scan (time incl. sampling)
    kv_generate(net, prompt, max_new_tokens=N, temperature=0.0)  # compile
    t0 = time.perf_counter()
    kv_generate(net, prompt, max_new_tokens=N, temperature=0.0)
    dt = time.perf_counter() - t0
    print(json.dumps({"bench": "decode", "mode": "kv_cache",
                      "tokens_per_sec": round(B * N / dt, 1),
                      "batch": B, "new_tokens": N,
                      "platform": platform}))
    sys.stdout.flush()

    # batch-1 latency (interactive serving).  prefill='batched' runs the
    # prompt as ONE causal forward, then N-1 scan decode steps; the timed
    # wall covers prefill + decode, so ms_per_token = wall / N is the
    # honest serving latency per emitted token.  Four variants: the
    # per-op scan step vs the fused one-kernel-per-token Pallas step
    # (ops/decode_fused.py, VERDICT r4 item 2), each bf16 and int8.
    p1 = prompt[:1]
    for wmode in ("native", "int8"):
        for fmode in ("off", "auto"):
            kw = dict(max_new_tokens=N, temperature=0.0, weights=wmode,
                      fused=fmode)
            kv_generate(net, p1, **kw)  # compile
            t0 = time.perf_counter()
            kv_generate(net, p1, **kw)
            dt = time.perf_counter() - t0
            tag = "kv_cache_batch1" + \
                ("_int8" if wmode == "int8" else "") + \
                ("_fused" if fmode == "auto" else "")
            print(json.dumps({"bench": "decode", "mode": tag,
                              "new_tokens_per_sec": round(N / dt, 1),
                              "ms_per_token": round(dt / N * 1e3, 3),
                              "batch": 1, "new_tokens": N, "prompt": P,
                              "platform": platform}))
            sys.stdout.flush()

    # full-recompute path (the reference-style loop); fewer tokens — it
    # retraces per length and does O(L^2) work
    n2 = min(N, 4)
    net.generate(prompt, max_new_tokens=2, temperature=0.0)  # warm traces
    t0 = time.perf_counter()
    net.generate(prompt, max_new_tokens=n2, temperature=0.0)
    dt = time.perf_counter() - t0
    print(json.dumps({"bench": "decode", "mode": "full_recompute",
                      "tokens_per_sec": round(B * n2 / dt, 1),
                      "batch": B, "new_tokens": n2,
                      "platform": platform}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
