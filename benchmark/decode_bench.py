#!/usr/bin/env python3
"""Autoregressive decode throughput: KV-cache (one compiled scan) vs the
full-recompute ``GPT.generate`` loop.  Prints one JSON line per mode.

Batch-1 arms sweep the per-token step implementation (unrolled per-layer
/ stacked-layer scan / Pallas megakernel where its TPU gate passes) and
report, next to the timings, the **ops/step column**: the optimized-HLO
instruction count of ONE compiled decode step
(``models.decode_step_program`` + ``profiler_xla.hlo_op_count``).  The
r4 profile showed decode is sequencer-bound (~230 device ops x ~2.5 us
of fixed per-op cost, BASELINE.md) — this column is the CAUSE metric the
stacked-scan path collapses, measurable on any backend.

The full run also carries the **ragged-arrival arm** (shared with
``serve_bench.py``): one ragged workload served as static padded
batches vs slot-pool continuous batching (``mxnet_tpu/serve/``) at
25/50/100% padded-batch occupancy — the serving-shaped comparison the
static arms can't express.

Every arm reports **tokens_per_dispatch** (ISSUE 17): useful tokens
emitted per executable dispatch.  The scan/loop arms are exactly 1.0 by
construction (one decode dispatch per token per lane); the
**speculative arm** (``spec_selfdraft``) decodes a repetitive-suffix
prompt on a ONE-slot pump-driven server with draft-and-verify on, and
its strict global ratio — tokens / (admit + step + verify dispatches)
— must clear > 1.5 (the n-gram self-drafts verify at high acceptance,
so each verify dispatch advances several positions).

``--smoke``: tiny geometry, no TPU — exercises the unrolled and stacked
arms plus the op-count column and asserts greedy parity between them;
gated in tier-1 like ``step_profile.py --smoke``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _step_ops(net, total, weights, fused, stacked):
    """ops/step for one compiled batch-1 decode step of this arm."""
    from mxnet_tpu import profiler_xla
    from mxnet_tpu.models import decode_step_program

    fn, args = decode_step_program(net, batch=1, total=total,
                                   weights=weights, fused=fused,
                                   stacked=stacked)
    return profiler_xla.hlo_op_count(fn, *args)


def run_spec_single(net, cfg, P, N):
    """ISSUE 17 speculative arm: one slot, repetitive-suffix prompt.

    A single request decodes on a pump-driven one-slot server with
    draft-and-verify ON; the prompt's repeated suffix gives the n-gram
    drafter material from the first step, so verifies advance several
    positions each.  Returns ``(prompt, toks, tokens_per_dispatch,
    accept_rate, dispatch_deltas, wall)`` where tokens_per_dispatch is
    the STRICT global ratio tokens / (admit + step + verify
    dispatches) — every dispatch the request cost, nothing amortised
    away."""
    from mxnet_tpu.serve import DecodeServer

    prompt = onp.tile(onp.arange(1, 5), -(-P // 4))[:P]
    srv = DecodeServer(net, max_total_len=P + N, pool_sizes=(1,),
                       spec=True, prefix_cache=False, autostart=False)
    warm = srv.submit(prompt, max_new_tokens=N)   # compile everything
    while srv.pump():
        pass
    warm.tokens(1)
    base = dict(srv.counters)
    t0 = time.perf_counter()
    stream = srv.submit(prompt, max_new_tokens=N)
    while srv.pump():
        pass
    wall = time.perf_counter() - t0
    toks = stream.tokens(1)
    d = {k: v - base[k] for k, v in dict(srv.counters).items()}
    disp = (d["admit_dispatches"] + d["step_dispatches"]
            + d["verify_dispatches"])
    tpd = len(toks) / max(disp, 1)
    acc = d["draft_accepted"] / max(d["draft_accepted"]
                                    + d["draft_rejected"], 1)
    srv.close()
    return prompt, toks, tpd, acc, d, wall


def smoke():
    """Tiny-geometry unrolled-vs-stacked decode: parity + op-count
    collapse, CPU-friendly (the tier-1 gate)."""
    import mxnet_tpu as mx
    from mxnet_tpu.models import GPT, GPTConfig, kv_generate

    mx.random.seed(0)
    cfg = GPTConfig(vocab_size=512, max_length=128, num_layers=2,
                    units=64, num_heads=4, hidden_size=128)
    net = GPT(cfg)
    net.initialize(mx.init.Normal(0.02))
    B, P, N = 2, 8, 16
    prompt = onp.random.RandomState(0).randint(0, cfg.vocab_size, (B, P))
    outs, rows = {}, []
    for arm, skw, wmode in (("unrolled", "off", "native"),
                            ("stacked", "on", "native"),
                            ("int8_unrolled", "off", "int8"),
                            ("int8_stacked", "on", "int8")):
        kv_generate(net, prompt, max_new_tokens=N, temperature=0.0,
                    stacked=skw, weights=wmode)  # compile
        t0 = time.perf_counter()
        outs[arm] = kv_generate(net, prompt, max_new_tokens=N,
                                temperature=0.0, stacked=skw,
                                weights=wmode)
        dt = time.perf_counter() - t0
        ops = _step_ops(net, P + N, wmode, "off", skw)
        rows.append((arm, ops))
        print(json.dumps({"bench": "decode_smoke", "mode": arm,
                          "ops_per_step": ops,
                          "ms_per_token": round(dt / N * 1e3, 3),
                          "tokens_per_dispatch": 1.0,  # 1 token/step scan
                          "batch": B, "new_tokens": N}))
    onp.testing.assert_array_equal(outs["stacked"], outs["unrolled"])
    onp.testing.assert_array_equal(outs["int8_stacked"],
                                   outs["int8_unrolled"])
    ops = dict(rows)
    assert ops["stacked"] < ops["unrolled"], rows
    assert ops["int8_stacked"] < ops["int8_unrolled"], rows
    print(f"# parity OK; ops/step {ops['unrolled']} -> {ops['stacked']}"
          f" (int8 {ops['int8_unrolled']} -> {ops['int8_stacked']})")

    # speculative arm (ISSUE 17): strict tokens/(admit+step+verify)
    # on a repetitive-suffix prompt must clear the > 1.5 acceptance
    # bar, and the served stream must match the offline greedy decode
    import jax
    platform = jax.devices()[0].platform
    Ns = 48
    sp_prompt, sp_toks, tpd, acc, d, wall = run_spec_single(
        net, cfg, P, Ns)
    print(json.dumps({"bench": "decode_smoke", "mode": "spec_selfdraft",
                      "tokens_per_dispatch": round(tpd, 3),
                      "accept_rate": round(acc, 3),
                      "admit_dispatches": d["admit_dispatches"],
                      "step_dispatches": d["step_dispatches"],
                      "verify_dispatches": d["verify_dispatches"],
                      "ms_per_token": round(wall / Ns * 1e3, 3),
                      "new_tokens": Ns, "platform": platform}))
    assert tpd > 1.5, f"spec tokens/dispatch {tpd:.2f} <= 1.5"
    ref = list(kv_generate(net, sp_prompt[None], max_new_tokens=Ns,
                           temperature=0.0)[0, sp_prompt.size:])
    assert sp_toks == ref, "spec stream != kv_generate"
    print(f"# spec OK: {tpd:.2f} tokens/dispatch at "
          f"{acc:.2f} accept, parity exact")
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny unrolled-vs-stacked arms + op-count "
                         "column only (tier-1 gate, runs on CPU in "
                         "seconds)")
    args = ap.parse_args()
    if args.smoke:
        return smoke()

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.models import GPT, GPTConfig, decode_mode, kv_generate

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    mx.random.seed(0)
    cfg = GPTConfig(vocab_size=32768, max_length=1024, num_layers=12,
                    units=768, num_heads=12, hidden_size=3072,
                    dtype="bfloat16" if on_tpu else "float32") \
        if on_tpu else GPTConfig(vocab_size=512, max_length=128,
                                 num_layers=2, units=64, num_heads=4,
                                 hidden_size=128)
    net = GPT(cfg)
    net.initialize(mx.init.Normal(0.02))
    B, P, N = (8, 32, 256) if on_tpu else (2, 8, 16)
    prompt = onp.random.RandomState(0).randint(0, cfg.vocab_size, (B, P))

    # KV-cache path: one compiled scan (time incl. sampling), default
    # step mode (stacked where supported)
    kv_generate(net, prompt, max_new_tokens=N, temperature=0.0)  # compile
    t0 = time.perf_counter()
    kv_generate(net, prompt, max_new_tokens=N, temperature=0.0)
    dt = time.perf_counter() - t0
    print(json.dumps({"bench": "decode", "mode": "kv_cache",
                      "step": decode_mode(net, B, P + N),
                      "tokens_per_sec": round(B * N / dt, 1),
                      "tokens_per_dispatch": 1.0,  # 1 token/step scan
                      "batch": B, "new_tokens": N,
                      "platform": platform}))
    sys.stdout.flush()

    # batch-1 latency (interactive serving).  prefill='batched' runs the
    # prompt as ONE causal forward, then N-1 scan decode steps; the timed
    # wall covers prefill + decode, so ms_per_token = wall / N is the
    # honest serving latency per emitted token.  Arms: per-layer
    # unrolled vs stacked-layer scan (any backend), the Pallas megakernel
    # where its gate passes (fused='on' raises otherwise), each with the
    # int8 weight stream where covered.
    p1 = prompt[:1]
    arms = [("native", "off", "off", "kv_cache_batch1"),
            ("native", "off", "on", "kv_cache_batch1_stacked"),
            ("native", "on", "off", "kv_cache_batch1_fused"),
            ("int8", "off", "off", "kv_cache_batch1_int8"),
            ("int8", "off", "on", "kv_cache_batch1_int8_stacked"),
            ("int8", "on", "off", "kv_cache_batch1_int8_fused")]
    for wmode, fmode, smode, tag in arms:
        kw = dict(max_new_tokens=N, temperature=0.0, weights=wmode,
                  fused=fmode, stacked=smode)
        try:
            kv_generate(net, p1, **kw)  # compile
        except MXNetError as e:
            print(json.dumps({"bench": "decode", "mode": tag,
                              "skipped": str(e)[:80],
                              "platform": platform}))
            sys.stdout.flush()
            continue
        t0 = time.perf_counter()
        kv_generate(net, p1, **kw)
        dt = time.perf_counter() - t0
        ops = _step_ops(net, P + N, wmode, fmode, smode)
        print(json.dumps({"bench": "decode", "mode": tag,
                          "new_tokens_per_sec": round(N / dt, 1),
                          "ms_per_token": round(dt / N * 1e3, 3),
                          "ops_per_step": ops,
                          "tokens_per_dispatch": 1.0,  # 1 token/step
                          "batch": 1, "new_tokens": N, "prompt": P,
                          "platform": platform}))
        sys.stdout.flush()

    # speculative-decoding arm (ISSUE 17): one slot, repetitive-suffix
    # prompt, draft-and-verify on — strict global tokens per dispatch
    sp_prompt, sp_toks, tpd, acc, d, wall = run_spec_single(
        net, cfg, P, N)
    print(json.dumps({"bench": "decode", "mode": "spec_selfdraft",
                      "new_tokens_per_sec": round(len(sp_toks) / wall, 1),
                      "tokens_per_dispatch": round(tpd, 3),
                      "accept_rate": round(acc, 3),
                      "admit_dispatches": d["admit_dispatches"],
                      "step_dispatches": d["step_dispatches"],
                      "verify_dispatches": d["verify_dispatches"],
                      "batch": 1, "new_tokens": N, "prompt": P,
                      "platform": platform}))
    sys.stdout.flush()
    assert tpd > 1.5, f"spec tokens/dispatch {tpd:.2f} <= 1.5"

    # ragged-arrival arm: the same ragged workload (per 8-request wave
    # one long request + seven short) served as static padded batches
    # (every lane decodes to the wave max) vs slot-pool continuous
    # batching (mxnet_tpu/serve/ — retired slots back-fill mid-flight).
    # Useful-token throughput at 25/50/100% padded-batch occupancy;
    # continuous wins at sparse occupancy wherever decode compute
    # dominates dispatch (TPU, or serve_bench.py --cpu-full on CPU).
    from benchmark.serve_bench import run_ragged
    S_r, N_r = 8, N
    for frac in (0.25, 0.5, 1.0):
        st, ct, occ, _ttfts = run_ragged(net, cfg, S_r, P, N_r, frac,
                                         2 * S_r)
        print(json.dumps({"bench": "decode",
                          "mode": f"ragged_occ={frac}",
                          "static_padded_tok_s": round(st, 1),
                          "continuous_tok_s": round(ct, 1),
                          "continuous_vs_static": round(ct / st, 3),
                          "tokens_per_dispatch": 1.0,  # spec=False
                          "occupancy": round(occ, 3),
                          "num_slots": S_r, "new_tokens": N_r,
                          "platform": platform}))
        sys.stdout.flush()

    # full-recompute path (the reference-style loop); fewer tokens — it
    # retraces per length and does O(L^2) work
    n2 = min(N, 4)
    net.generate(prompt, max_new_tokens=2, temperature=0.0)  # warm traces
    t0 = time.perf_counter()
    net.generate(prompt, max_new_tokens=n2, temperature=0.0)
    dt = time.perf_counter() - t0
    print(json.dumps({"bench": "decode", "mode": "full_recompute",
                      "tokens_per_sec": round(B * n2 / dt, 1),
                      "tokens_per_dispatch": 1.0,  # 1 forward/token
                      "batch": B, "new_tokens": n2,
                      "platform": platform}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
