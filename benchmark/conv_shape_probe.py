#!/usr/bin/env python3
"""Per-shape conv fwd/dgrad/wgrad probe for ResNet-50 (BASELINE config 2).

Times every unique convolution of ResNet-50 v1 standalone — forward,
input-gradient (dgrad) and weight-gradient (wgrad) separately — with the
slope method (T(n2)-T(n1) over chained in-jit iterations, cancelling the
TPU-tunnel dispatch RTT exactly; see BASELINE.md r5 methodology).  This
is the measurement VERDICT r4 item 1 asks for: where the 49 ms of
backward-conv time actually lives, per shape, against the 197 TF/s MXU
peak and ~819 GB/s HBM roofline of a v5e chip.

Reference counterpart: the reference autotunes per-shape cuDNN
algorithms (SURVEY.md §3.1 cuDNN autotuned conv paths,
``MXNET_CUDNN_AUTOTUNE_DEFAULT``); the TPU rebuild's analog is choosing
XLA vs a Pallas kernel per shape from measurements like these.

  python benchmark/conv_shape_probe.py [--bs 256] [--n1 10] [--n2 40]
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
from jax import lax

PEAK_TF = 197.0
HBM_GBS = 819.0


def resnet50_convs(bs):
    """(name, k, stride, cin, cout, hw_in, count) for every unique conv
    of ResNet-50 v1 at batch ``bs`` (v1: stride sits in the block's
    first 1x1 for stages 2-4; counts fold identical shapes)."""
    out = [("conv1_7x7s2", 7, 2, 3, 64, 224, 1)]
    # per stage: (hw of the 3x3 work, cin_block_in, bottleneck c, cout, blocks)
    stages = [(56, 64, 64, 256, 3), (28, 256, 128, 512, 4),
              (14, 512, 256, 1024, 6), (7, 1024, 512, 2048, 3)]
    for si, (hw, cin, cb, cout, nb) in enumerate(stages):
        s = 1 if si == 0 else 2
        hw_in = hw * s  # first block's input spatial
        # first block: 1x1 reduce (maybe strided), 3x3, 1x1 expand, downsample
        out.append((f"s{si+1}b1_1x1r", 1, s, cin, cb, hw_in, 1))
        out.append((f"s{si+1}_3x3", 3, 1, cb, cb, hw, nb))
        out.append((f"s{si+1}_1x1e", 1, 1, cb, cout, hw, nb))
        out.append((f"s{si+1}_ds", 1, s, cin, cout, hw_in, 1))
        if nb > 1:  # remaining blocks' 1x1 reduce (cout -> cb)
            out.append((f"s{si+1}_1x1r", 1, 1, cout, cb, hw, nb - 1))
    return out


def conv_fn(k, stride, layout="NCHW"):
    pad = [(k // 2, k // 2)] * 2
    dn = (layout, "OIHW", layout)

    def f(x, w):
        return lax.conv_general_dilated(
            x, w, (stride, stride), pad, dimension_numbers=dn)
    return f


def chained(op):
    """One jitted harness per op with a DYNAMIC trip count: iteration i
    scales the varying arg by a runtime ``ones`` vector (a traced input,
    so XLA cannot constant-fold it to 1.0 and hoist the op out of the
    loop) and accumulates the SUM of the whole output — consuming only
    one element lets XLA narrow the conv to computing that element
    (measured: "26 million TF/s"), the failure mode of the second
    version of this probe.  The sum fuses into the conv epilogue, so
    the extra cost is far below the conv itself."""
    def run(n, ones, *args):
        def body(i, acc):
            a0 = args[0] * ones[i % ones.shape[0]]
            y = op(a0, *args[1:])
            return acc + jnp.sum(y.astype(jnp.float32))
        return lax.fori_loop(0, n, body, jnp.float32(0))
    return jax.jit(run)


def slope_time(f, args, n1, n2, reps=3):
    """T(n2)-T(n1) over (n2-n1): cancels dispatch/readback RTT.

    The tunnel's RTT jitter is ~50-100 ms, so the iteration-count DELTA
    must put >= ~0.5 s of device work between the two measurements or
    the slope is noise (the r5 first-probe failure mode: 30 ms of
    signal under 100 ms of jitter produced 0.000-ms ops and "26
    million TF/s").  A pilot run sizes n2 adaptively.  Retries the
    compile on transient tunnel drops."""
    ones = jnp.ones((8,), args[0].dtype)
    for attempt in range(3):
        try:
            float(f(n1, ones, *args))  # one compile serves all counts
            break
        except Exception:
            if attempt == 2:
                raise
            time.sleep(5.0)
    # pilot with an RTT-cancelling delta: a plain T(n1)/n1 estimate is
    # RTT-dominated for sub-ms ops and under-sizes n2 (the "0.000 ms
    # op" failure mode)
    t1 = time.time(); float(f(n1, ones, *args)); t1 = time.time() - t1
    t5 = time.time(); float(f(5 * n1, ones, *args)); t5 = time.time() - t5
    per_it = max((t5 - t1) / (4 * n1), 2e-5)
    n2 = max(n2, n1 + max(500, int(0.8 / per_it)))
    n2 = min(n2, n1 + 20000)
    ts = []
    for n in (n1, n2):
        best = None
        for _ in range(reps):
            t0 = time.time()
            float(f(n, ones, *args))
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        ts.append(best)
    return max((ts[1] - ts[0]) / (n2 - n1), 1e-9)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=256)
    ap.add_argument("--n1", type=int, default=10)
    ap.add_argument("--n2", type=int, default=40)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--only", default="",
                    help="comma-separated substring filter on shape names")
    ap.add_argument("--layout", default="NCHW", choices=["NCHW", "NHWC"])
    args = ap.parse_args()
    only = [s for s in args.only.split(",") if s]
    dt_ = jnp.dtype(args.dtype)
    bs = args.bs

    import numpy as onp
    rng = onp.random.RandomState(0)
    rows = []
    tot = {"fwd": 0.0, "dgrad": 0.0, "wgrad": 0.0}
    print(f"{'shape':16s} {'cnt':>3s} | {'fwd ms':>8s} {'TF/s':>6s} | "
          f"{'dgrad ms':>8s} {'TF/s':>6s} | {'wgrad ms':>8s} {'TF/s':>6s} | "
          f"{'GB(min)':>7s} {'AI':>5s}")
    for name, k, s, cin, cout, hw, cnt in resnet50_convs(bs):
        if only and not any(p in name for p in only):
            continue
        f = conv_fn(k, s, args.layout)
        hw_out = hw // s
        if args.layout == "NHWC":
            x = jnp.asarray(rng.rand(bs, hw, hw, cin) - 0.5, dt_)
            y = jnp.asarray(rng.rand(bs, hw_out, hw_out, cout) - 0.5,
                            dt_)
        else:
            x = jnp.asarray(rng.rand(bs, cin, hw, hw) - 0.5, dt_)
            y = jnp.asarray(rng.rand(bs, cout, hw_out, hw_out) - 0.5,
                            dt_)
        w = jnp.asarray(rng.rand(cout, cin, k, k) - 0.5, dt_)
        flops = 2 * bs * hw_out * hw_out * cin * cout * k * k

        def dgrad(dy, ww):
            _, pb = jax.vjp(lambda xx: f(xx, ww), x)
            return pb(dy)[0]

        def wgrad(dy, xx):
            _, pb = jax.vjp(lambda ww: f(xx, ww), w)
            return pb(dy)[0]

        t_f = slope_time(chained(f), (x, w), args.n1, args.n2)
        t_d = slope_time(chained(dgrad), (y, w), args.n1, args.n2)
        t_w = slope_time(chained(wgrad), (y, x), args.n1, args.n2)
        # minimal one-pass traffic for ONE of the three passes (read two
        # operands, write one), bf16:
        nbytes = dt_.itemsize
        gb = (x.size + w.size + y.size) * nbytes / 1e9
        ai = flops / (gb * 1e9)
        row = {"name": name, "count": cnt, "k": k, "stride": s,
               "cin": cin, "cout": cout, "hw": hw,
               "fwd_ms": t_f * 1e3, "dgrad_ms": t_d * 1e3,
               "wgrad_ms": t_w * 1e3, "tf_fwd": flops / t_f / 1e12,
               "tf_dgrad": flops / t_d / 1e12,
               "tf_wgrad": flops / t_w / 1e12,
               "min_gb": gb, "ai": ai}
        rows.append(row)
        tot["fwd"] += cnt * t_f * 1e3
        tot["dgrad"] += cnt * t_d * 1e3
        tot["wgrad"] += cnt * t_w * 1e3
        print(f"{name:16s} x{cnt:2d} | {t_f*1e3:8.3f} {row['tf_fwd']:6.1f} | "
              f"{t_d*1e3:8.3f} {row['tf_dgrad']:6.1f} | "
              f"{t_w*1e3:8.3f} {row['tf_wgrad']:6.1f} | "
              f"{gb:7.3f} {ai:5.0f}")
    print(f"\ncount-weighted totals (ms/step): fwd {tot['fwd']:.1f}  "
          f"dgrad {tot['dgrad']:.1f}  wgrad {tot['wgrad']:.1f}  "
          f"bwd {tot['dgrad']+tot['wgrad']:.1f}")
    with open("/tmp/conv_shape_probe.json", "w") as fh:
        json.dump({"bs": bs, "rows": rows, "totals": tot}, fh, indent=1)
    print("wrote /tmp/conv_shape_probe.json")


if __name__ == "__main__":
    main()
