#!/usr/bin/env python3
"""Recording-driven decode/serve sweep: run the decode_bench and
serve_bench arms as subprocesses under ``MXNET_TELEMETRY_JSONL``,
re-verify the serving invariants from each recording with
``tools/telemetry_report.py --check-serve``, and print the measured
rows BASELINE.md-ready (one markdown table per bench).

This is the one-command path from "fresh checkout" to "the dispatch
table in BASELINE.md": every number it prints went through the
telemetry stream, so the ladder-bounded-compile / zero-retrace /
draft-ledger invariants were checked against the SAME run the rows
came from — a row cannot land in BASELINE.md from a run that violated
the serving contract.

    python benchmark/tpu_sweep.py --smoke        # CPU, minutes
    python benchmark/tpu_sweep.py                # full profiles
    python benchmark/tpu_sweep.py --dry-run      # plan only

``--smoke`` forwards each bench's ``--smoke`` profile (the tier-1
geometry, runs on CPU); ``--dry-run`` prints the planned commands and
environment without executing (tier-1 covers it). Recordings land in
``--out`` (default: a temp directory, deleted unless ``--keep``).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the arms each bench contributes to the BASELINE.md dispatch table;
# anything else the bench prints is measured but not a headline row
WANTED = {
    "decode": ("unrolled", "stacked", "int8_stacked", "kv_cache",
               "kv_cache_batch1", "kv_cache_batch1_stacked",
               "spec_selfdraft"),
    "serve": ("saturated", "ragged_occ=0.25", "ragged_occ=0.5",
              "ragged_occ=1.0", "ragged_spec", "kv_quant_residency",
              "prefix_hit"),
    "dist": ("single", "pod"),
}
# columns worth a BASELINE.md reader's attention, in print order
COLUMNS = ("tokens_per_sec", "new_tokens_per_sec", "tokens_per_dispatch",
           "accept_rate", "ops_per_step", "ms_per_token",
           "dispatches_per_step", "procs",
           "continuous_vs_static", "resident_x", "greedy_agreement",
           "p50_ttft_ms", "p99_ttft_ms",
           "p50_hit_ttft_ms", "occupancy", "platform")


def plan(args, out_dir):
    """The sweep plan: (name, argv, recording-path) per bench."""
    py = sys.executable
    here = os.path.dirname(os.path.abspath(__file__))
    jobs = []
    for name in ("decode", "serve", "dist"):
        argv = [py, os.path.join(here, f"{name}_bench.py")]
        if args.smoke:
            argv.append("--smoke")
        jobs.append((name, argv, os.path.join(out_dir, f"{name}.jsonl")))
    return jobs


def run_job(name, argv, rec_path, timeout):
    """Run one bench under a JSONL recording; return its stdout rows."""
    env = dict(os.environ)
    env["MXNET_TELEMETRY_JSONL"] = rec_path
    t0 = time.perf_counter()
    proc = subprocess.run(argv, env=env, capture_output=True,
                          text=True, timeout=timeout)
    wall = time.perf_counter() - t0
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout)
        raise SystemExit(f"tpu_sweep: {name} bench failed "
                         f"(exit {proc.returncode})")
    rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            pass
    print(f"# {name}: {len(rows)} rows in {wall:.1f}s -> {rec_path}")
    return rows


def check_recording(name, rec_path):
    """Re-verify the serving invariants from the recording alone."""
    from tools.telemetry_report import check_serve, load
    events = load(rec_path)
    failures = check_serve(events)
    if failures:
        for f in failures:
            print(f"CHECK FAILED ({name}): {f}", file=sys.stderr)
        raise SystemExit(f"tpu_sweep: {name} recording violated the "
                         f"serving invariants")
    print(f"# {name}: serve checks OK over {len(events)} recorded events")


def baseline_table(name, rows):
    """BASELINE.md-ready markdown for one bench's headline arms."""
    picked = [r for r in rows if r.get("mode") in WANTED[name]]
    if not picked:
        return f"(no {name} headline rows — bench printed none)"
    cols = [c for c in COLUMNS if any(c in r for r in picked)]
    out = [f"| arm | {' | '.join(cols)} |",
           f"|---|{'---|' * len(cols)}"]
    for r in picked:
        cells = [str(r.get(c, "-")) for c in cols]
        out.append(f"| {r['mode']} | {' | '.join(cells)} |")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Run the decode/serve bench arms under a telemetry "
                    "recording, re-check the serving invariants from "
                    "it, and print BASELINE.md-ready rows.")
    ap.add_argument("--smoke", action="store_true",
                    help="forward each bench's --smoke profile "
                         "(CPU-sized, minutes)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the planned commands and recording "
                         "paths without executing")
    ap.add_argument("--out", default=None,
                    help="directory for the JSONL recordings "
                         "(default: temp dir, deleted unless --keep)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the recordings directory")
    ap.add_argument("--timeout", type=float, default=1800.0,
                    help="per-bench subprocess timeout, seconds")
    args = ap.parse_args(argv)

    out_dir = args.out or tempfile.mkdtemp(prefix="tpu_sweep_")
    os.makedirs(out_dir, exist_ok=True)
    jobs = plan(args, out_dir)

    if args.dry_run:
        for name, cmd, rec in jobs:
            print(f"{name}: MXNET_TELEMETRY_JSONL={rec} "
                  + " ".join(cmd))
        print(f"# dry run: 0 of {len(jobs)} benches executed; "
              f"rows would be checked via telemetry_report.check_serve")
        return 0

    tables = []
    try:
        for name, cmd, rec in jobs:
            rows = run_job(name, cmd, rec, args.timeout)
            if name != "dist":
                # the dist bench has no serving contract to re-check;
                # its discipline gate (1 dispatch/step, 0 steady
                # compiles) is enforced inside dist_bench itself
                check_recording(name, rec)
            tables.append((name, baseline_table(name, rows)))
    finally:
        if args.out is None and not args.keep:
            shutil.rmtree(out_dir, ignore_errors=True)
        elif args.keep or args.out:
            print(f"# recordings kept in {out_dir}")

    for name, table in tables:
        print(f"\n## {name}_bench ({'smoke' if args.smoke else 'full'})")
        print(table)
    return 0


if __name__ == "__main__":
    sys.exit(main())
