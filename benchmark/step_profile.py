#!/usr/bin/env python3
"""Per-XLA-op profile of a full fused train step (ResNet-50 or BERT).

Uses ``mxnet_tpu.profiler_xla`` (the trace-parsing device profiler,
SURVEY.md §5.1 parity) to attribute every microsecond of the compiled
SPMD step to an HLO op / source jaxpr op — the tool the reference gets
from engine hooks, recovered here from the ``jax.profiler`` device trace.

  python benchmark/step_profile.py resnet  [--bs 256] [--by tf_op]
  python benchmark/step_profile.py bert    [--bs 64]  [--by category]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

from benchmark import mem_fields

PEAK_TFLOPS = 197.0


def emit_row(row):
    """Measured row into the telemetry event stream (kind ``bench``) —
    a ``MXNET_TELEMETRY_JSONL`` recording carries the phase rows next
    to the compile events in one schema (``tools/telemetry_report.py``
    renders both; the printed human tables stay as-is)."""
    from mxnet_tpu import telemetry
    telemetry.emit("bench", **row)


def build_resnet(bs):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet

    on_tpu = jax.devices()[0].platform == "tpu"
    hw = 224 if on_tpu else 32
    mx.random.seed(0)
    net = get_resnet(1, 50, classes=1000)
    net.initialize(mx.init.Xavier())
    if on_tpu:
        net.cast("bfloat16")
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        mesh=parallel.make_mesh({"dp": len(jax.devices())}))
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.rand(bs, 3, hw, hw).astype(
        "bfloat16" if on_tpu else "float32"))
    y = mx.nd.array(rng.randint(0, 1000, bs).astype(onp.float32))
    return trainer, x, y


def build_bert(bs):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import BERTConfig, BERTModel

    on_tpu = jax.devices()[0].platform == "tpu"
    seq = 128
    mx.random.seed(0)
    cfg = BERTConfig(vocab_size=30528, max_length=seq, num_layers=12,
                     units=768, num_heads=12, hidden_size=3072,
                     dtype="bfloat16" if on_tpu else "float32")
    bert = BERTModel(cfg, use_pooler=False, use_mlm=True)

    class _MLMHeadOnly(gluon.Block):
        def __init__(self):
            super().__init__()
            self.bert = bert

        def forward(self, tokens):
            return self.bert(tokens)[-1]

    net = _MLMHeadOnly()
    net.initialize(mx.init.Normal(0.02))
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
        {"learning_rate": 1e-4},
        mesh=parallel.make_mesh({"dp": len(jax.devices())}))
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randint(0, cfg.vocab_size, (bs, seq)))
    y = mx.nd.array(rng.randint(0, cfg.vocab_size, (bs, seq)))
    return trainer, x, y


def build_gpt(bs):
    """BASELINE config 5: GPT-2 774M (36L/1280U/20H/5120FF, seq 512) —
    same geometry as benchmark/transformer_bench.py."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import GPT, GPTConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    mx.random.seed(0)
    cfg = GPTConfig(vocab_size=50304, max_length=512, num_layers=36,
                    units=1280, num_heads=20, hidden_size=5120,
                    dtype="bfloat16") if on_tpu else \
        GPTConfig(vocab_size=512, max_length=64, num_layers=2, units=64,
                  num_heads=4, hidden_size=128)
    gpt = GPT(cfg)
    gpt.initialize(mx.init.Normal(0.02))
    trainer = parallel.SPMDTrainer(
        gpt, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
        {"learning_rate": 1e-4},
        mesh=parallel.make_mesh({"dp": len(jax.devices())}))
    rng = onp.random.RandomState(0)
    L = 512 if on_tpu else 16
    toks = rng.randint(0, cfg.vocab_size, (bs, L + 1))
    return trainer, mx.nd.array(toks[:, :-1]), mx.nd.array(toks[:, 1:])


def build_transformer(bs):
    """BASELINE config 4: Transformer-big seq2seq (1024U/4096FF/16H,
    6+6 layers, seq 256)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import TransformerSeq2Seq as Transformer

    on_tpu = jax.devices()[0].platform == "tpu"
    V, L = (32768, 256) if on_tpu else (512, 16)
    mx.random.seed(0)
    net = Transformer(V, units=1024 if on_tpu else 64,
                      hidden_size=4096 if on_tpu else 128,
                      num_heads=16 if on_tpu else 4,
                      num_enc_layers=6 if on_tpu else 2,
                      num_dec_layers=6 if on_tpu else 2,
                      max_length=L, dropout=0.0,
                      dtype="bfloat16" if on_tpu else "float32")
    net.initialize(mx.init.Xavier())

    class _Wrap(gluon.Block):
        def __init__(self):
            super().__init__()
            self.net = net

        def forward(self, both):
            return self.net(both[:, 0], both[:, 1])

    wrap = _Wrap()
    rng = onp.random.RandomState(0)
    src = rng.randint(0, V, (bs, L))
    tgt = rng.randint(0, V, (bs, L))
    both = onp.stack([src, tgt], axis=1)
    trainer = parallel.SPMDTrainer(
        wrap, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-4},
        mesh=parallel.make_mesh({"dp": len(jax.devices())}))
    return trainer, mx.nd.array(both), mx.nd.array(tgt)


def measure_optimizer_apply(params, opt_name, reps=10):
    """Fused-vs-legacy optimizer-apply phase over a ParameterDict (the
    imperative ``gluon.Trainer`` path): synthesizes grads, times ``reps``
    steady-state steps per mode, and counts optimizer-apply dispatches.
    Returns ``(n_params, [(mode, dispatches_per_step, ms_per_step)])``.
    One implementation shared by step_profile and step_breakdown so the
    two benchmarks can't drift on methodology."""
    import time

    import jax.numpy as jnp

    from mxnet_tpu import gluon
    from mxnet_tpu.ndarray.ndarray import waitall
    from mxnet_tpu.optimizer import optimizer as opt_impl

    live = [p for p in params.values() if p.grad_req != "null"]
    rng = onp.random.RandomState(0)
    for p in live:
        p.grad()._rebind(jnp.asarray(rng.randn(*p.shape) * 1e-3,
                                     p.data()._data.dtype))
    prev = os.environ.get("MXNET_FUSED_OPTIMIZER")
    rows = []
    try:
        for mode, env in (("fused", "1"), ("legacy", "0")):
            os.environ["MXNET_FUSED_OPTIMIZER"] = env
            tr = gluon.Trainer(params, opt_name,
                               {"learning_rate": 1e-4}, kvstore=None)
            tr.step(1)          # compile + state creation
            waitall()
            opt_impl.reset_apply_counters()
            t0 = time.perf_counter()
            for _ in range(reps):
                tr.step(1)
            waitall()
            dt = (time.perf_counter() - t0) / reps * 1e3
            c = opt_impl.apply_counters
            disp = (c["fused_calls"] + c["fallback_params"]) / reps
            rows.append((mode, disp, dt))
    finally:
        if prev is None:
            os.environ.pop("MXNET_FUSED_OPTIMIZER", None)
        else:
            os.environ["MXNET_FUSED_OPTIMIZER"] = prev
    return len(live), rows


def measure_fused_step(n_layers=200, units=64, bs=32, reps=10,
                       intervals=(1, 4), opt_name="adamw", warm=2):
    """Fused-step phase: the whole train step (forward + loss + backward
    + optimizer apply) as ONE donated-buffer XLA executable
    (``Trainer.fused_step``) vs today's phase-by-phase chain (jitted
    CachedOp forward → tape backward → fused ``multi_update`` apply) on
    the BASELINE 200-param workload (``n_layers`` chained bias-free
    Dense(units) layers = n_layers (units,units) f32 params).  Sweeps the
    gradient-accumulation window (``Trainer(update_interval=N)``): the N
    amortizes the optimizer apply + its host bookkeeping over the window.
    Returns ``(n_params, [(mode, host_dispatches_per_step, ms_per_step)])``
    — one implementation shared by step_profile and step_breakdown.
    ``host_dispatches_per_step`` counts registry invokes + jitted apply
    calls on the phase path, and fused-step executable invocations on the
    fused path (exactly 1)."""
    import time

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.fused_step import (step_counters,
                                            reset_step_counters)
    from mxnet_tpu.ndarray.ndarray import waitall
    from mxnet_tpu.ops import registry as reg
    from mxnet_tpu.optimizer import optimizer as opt_impl

    rng = onp.random.RandomState(0)
    X = rng.randn(bs, units).astype(onp.float32)
    Y = rng.randn(bs, 1).astype(onp.float32)
    loss_l = gluon.loss.L2Loss()

    def build():
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            for _ in range(n_layers - 1):
                net.add(nn.Dense(units, use_bias=False, in_units=units))
            net.add(nn.Dense(1, use_bias=False, in_units=units))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        return net

    rows = []

    # -- phase-by-phase (today's path) --------------------------------- #
    net = build()
    trainer = gluon.Trainer(net.collect_params(), opt_name,
                            {"learning_rate": 1e-4}, kvstore=None)
    x, y = mx.nd.array(X), mx.nd.array(Y)

    def phase_step():
        with mx.autograd.record():
            loss = loss_l(net(x), y)
        loss.backward()
        trainer.step(bs)
        return loss

    for _ in range(warm):
        phase_step()
    waitall()
    invokes = [0]
    orig_invoke = reg.invoke

    def counting_invoke(*a, **k):
        invokes[0] += 1
        return orig_invoke(*a, **k)

    reg.invoke = counting_invoke
    opt_impl.reset_apply_counters()
    try:
        t0 = time.perf_counter()
        for _ in range(reps):
            phase_step()
        waitall()
        dt = (time.perf_counter() - t0) / reps * 1e3
    finally:
        reg.invoke = orig_invoke
    disp = (invokes[0] + opt_impl.apply_counters["fused_calls"]
            + opt_impl.apply_counters["fallback_params"]) / reps
    rows.append(("phase-by-phase", disp, dt))

    # -- fused step, accumulate window sweep --------------------------- #
    for N in intervals:
        net = build()
        trainer = gluon.Trainer(net.collect_params(), opt_name,
                                {"learning_rate": 1e-4}, kvstore=None,
                                update_interval=N)

        def loss_fn(xx, yy):
            return loss_l(net(xx), yy)

        # two full windows of warmup: the second window re-executes both
        # executables on buffers PRODUCED by them (donation steady state)
        warm_n = max(warm, 2 * N) + (-max(warm, 2 * N)) % N
        for _ in range(warm_n):  # compile micro + apply executables
            trainer.fused_step(loss_fn, x, y)
        waitall()
        reset_step_counters()
        reps_n = max(N, reps - reps % N)  # whole windows only
        t0 = time.perf_counter()
        for _ in range(reps_n):
            trainer.fused_step(loss_fn, x, y)
        waitall()
        dt = (time.perf_counter() - t0) / reps_n * 1e3
        assert step_counters["compiles"] == 0, "retraced in steady state"
        disp = step_counters["dispatches"] / reps_n
        rows.append((f"fused step, N={N}", disp, dt))

    n_params = len([p for p in net.collect_params().values()
                    if p.grad_req != "null"])
    return n_params, rows


def train_step_op_count_smoke():
    """Tiny-BERT SPMD train-step HLO op count (the tier-1 gate for the
    static sequencer-overhead metric): builds a 2-layer BERT trainer and
    prints ``SPMDTrainer.step_hlo_op_count`` — the same counter the full
    ``bert`` run reports, whose BASELINE.md round-3 anatomy is ~5,300
    ops x ~1 us of fixed per-op cost (the wall-vs-device MFU gap)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import BERTConfig, BERTModel

    mx.random.seed(0)
    cfg = BERTConfig(vocab_size=512, max_length=32, num_layers=2,
                     units=64, num_heads=4, hidden_size=128)
    bert = BERTModel(cfg, use_pooler=False, use_mlm=True)

    class _MLMHeadOnly(gluon.Block):
        def __init__(self):
            super().__init__()
            self.bert = bert

        def forward(self, tokens):
            return self.bert(tokens)[-1]

    net = _MLMHeadOnly()
    net.initialize(mx.init.Normal(0.02))
    trainer = parallel.SPMDTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
        {"learning_rate": 1e-4},
        mesh=parallel.make_mesh({"dp": len(jax.devices())}))
    rng = onp.random.RandomState(0)
    bs = max(8, len(jax.devices()))
    x = mx.nd.array(rng.randint(0, cfg.vocab_size, (bs, 16)))
    y = mx.nd.array(rng.randint(0, cfg.vocab_size, (bs, 16)))
    n = trainer.step_hlo_op_count(x, y)
    print(f"\ntrain-step HLO op count (tiny BERT, 2L): {n}")
    emit_row({"bench": "step_profile", "mode": "train_step_op_count",
              "model": "tiny-bert-2l", "hlo_ops": n})
    return n


def profile_fused_step(smoke=False):
    """Fused-step phase rows (imperative Trainer path): ms/step and
    host-dispatch count, phase-by-phase vs one-executable, with the
    gradient-accumulation window sweep."""
    kw = dict(n_layers=8, units=8, bs=4, reps=3, intervals=(1, 2),
              warm=2) if smoke else {}
    n, rows = measure_fused_step(**kw)
    mem = mem_fields("gluon.fused_step")
    print(f"\nfused-step phase (imperative Trainer, {n}-param chain, "
          f"{'smoke' if smoke else 'baseline'} workload):")
    if mem:
        print(f"  executable memory (CPU-profile buffer sizes): "
              f"temp {mem['mem_temp_mb']} MB, "
              f"peak {mem['mem_peak_mb']} MB")
    for mode, disp, dt in rows:
        print(f"  {mode:18s}: {disp:6.0f} host dispatches/step   "
              f"{dt:8.2f} ms/step")
        emit_row({"bench": "step_profile", "mode": "fused_step_phase",
                  "arm": mode, "n_params": n,
                  "workload": "smoke" if smoke else "baseline",
                  "dispatches_per_step": round(disp, 2),
                  "ms_per_step": round(dt, 3), **mem})
    return rows


def profile_checkpoint(smoke=False):
    """Checkpoint-stall phase rows (ISSUE 15 acceptance): what one
    ``mx.checkpoint`` save costs the training loop, per mode.  The sync
    arm pays snapshot + atomic write inline; the async arm pays ONLY
    the device→host snapshot (``save()`` returns once the values are
    host-resident — the donation-safety contract — and the fsync+rename
    commit happens on the writer thread).  The stall is the measured
    ``save()`` wall time; steady-state step time with a save every
    step quantifies the residual overlap cost."""
    import shutil
    import tempfile
    import time

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.ndarray.ndarray import waitall

    n_layers, units, bs = (8, 8, 4) if smoke else (50, 64, 32)
    reps = 3 if smoke else 10
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(bs, units).astype(onp.float32))
    y = mx.nd.array(rng.randn(bs, 1).astype(onp.float32))
    loss_l = gluon.loss.L2Loss()

    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        for _ in range(n_layers - 1):
            net.add(nn.Dense(units, use_bias=False, in_units=units))
        net.add(nn.Dense(1, use_bias=False, in_units=units))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adamw",
                            {"learning_rate": 1e-4}, kvstore=None)

    def loss_fn(bx, by):
        return loss_l(net(bx), by)

    for _ in range(2):
        trainer.fused_step(loss_fn, x, y)
    waitall()

    def run(mode):
        tmp = tempfile.mkdtemp(prefix="mxnet_ckpt_bench_")
        mgr = None
        if mode != "no-save":
            mgr = mx.checkpoint.CheckpointManager(
                tmp, max_to_keep=2, async_save=(mode == "async-save"))
        stalls = []
        t0 = time.perf_counter()
        for k in range(reps):
            trainer.fused_step(loss_fn, x, y)
            if mgr is not None:
                s0 = time.perf_counter()
                mgr.save(k + 1, net, trainer)
                stalls.append(time.perf_counter() - s0)
        if mgr is not None:
            mgr.wait_until_finished()
        waitall()
        step_ms = (time.perf_counter() - t0) / reps * 1e3
        if mgr is not None:
            mgr.close()
        shutil.rmtree(tmp, ignore_errors=True)
        stall_ms = (sum(stalls) / len(stalls) * 1e3) if stalls else 0.0
        return step_ms, stall_ms

    print(f"\ncheckpoint phase ({n_layers}-layer chain, save every "
          f"step, {'smoke' if smoke else 'baseline'} workload):")
    rows = []
    for mode in ("no-save", "sync-save", "async-save"):
        step_ms, stall_ms = run(mode)
        rows.append((mode, step_ms, stall_ms))
        print(f"  {mode:10s}: {step_ms:8.2f} ms/step   "
              f"save stall {stall_ms:8.2f} ms")
        emit_row({"bench": "step_profile", "mode": "checkpoint_phase",
                  "arm": mode, "n_layers": n_layers,
                  "workload": "smoke" if smoke else "baseline",
                  "ms_per_step": round(step_ms, 3),
                  "save_stall_ms": round(stall_ms, 3)})
    return rows


def profile_optimizer_apply(trainer, iters=10):
    """Optimizer-apply phase row for the IMPERATIVE Trainer path (the
    API-parity path the SPMD profile above doesn't cover): the fused
    multi-tensor apply collapses the per-step host->device dispatch count
    from O(#params) to O(#groups) — this prints both counts and ms/step
    so the collapse is measurable per model."""
    n, rows = measure_optimizer_apply(
        trainer._block.collect_params(),
        type(trainer.optimizer).__name__.lower(), reps=iters)
    print(f"\noptimizer-apply phase (imperative Trainer, {n} params):")
    for mode, disp, dt in rows:
        print(f"  {mode:7s}: {disp:6.0f} optimizer-apply dispatches/step   "
              f"{dt:8.2f} ms/step")
        emit_row({"bench": "step_profile",
                  "mode": "optimizer_apply_phase", "arm": mode,
                  "n_params": n,
                  "dispatches_per_step": round(disp, 2),
                  "ms_per_step": round(dt, 3)})


def profile_input_overlap(trainer, x, y, steps=8, depth=2):
    """Input-pipeline / H2D overlap phase rows: feeds the compiled step
    from a host batch source (synthetic decode+augment work per batch)
    synchronously — input + H2D serialized into the step latency, the
    pre-PR DataLoader reality — vs through the depth-``depth``
    ``DevicePrefetchIter`` ring placed PRE-SHARDED with the trainer's own
    batch-axis ``NamedSharding`` (the ``DataLoader(device=sharding)``
    path).  With the ring, steady-state ms/step ≈ max(input, compute)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec

    from mxnet_tpu import nd
    from mxnet_tpu.gluon.data.dataloader import DevicePrefetchIter

    hx, hy = x.asnumpy(), y.asnumpy()
    sharding = NamedSharding(trainer._mesh, PartitionSpec(trainer._dp_axis))

    def host_batch():
        # stand-in for decode + augment: one smoothing pass over the batch
        out = hx
        for ax in range(max(1, hx.ndim - 1), hx.ndim):
            out = (onp.roll(out, 1, ax) + out + onp.roll(out, -1, ax)) / 3
        return out.astype(hx.dtype)

    def batches(n):
        for _ in range(n):
            yield (host_batch(), hy)

    t0 = time.perf_counter()
    for _ in batches(steps):
        pass
    input_ms = (time.perf_counter() - t0) / steps * 1e3

    def run(ring_depth):
        it = DevicePrefetchIter(batches(steps + 2), sharding,
                                depth=ring_depth,
                                background=ring_depth > 0)
        bx, by = next(it)  # warm ring + placement-signature executable
        trainer.step(bx, by).wait_to_read()
        t0 = time.perf_counter()
        n = 0
        for bx, by in it:
            trainer.step(bx, by).wait_to_read()
            n += 1
            if n == steps:
                break
        dt = (time.perf_counter() - t0) / n * 1e3
        it.close()
        return dt

    prev = os.environ.get("MXNET_DEVICE_PREFETCH")
    try:
        os.environ["MXNET_DEVICE_PREFETCH"] = "0"
        sync_ms = run(0)
        os.environ["MXNET_DEVICE_PREFETCH"] = str(depth)
        overlap_ms = run(depth)
    finally:
        if prev is None:
            os.environ.pop("MXNET_DEVICE_PREFETCH", None)
        else:
            os.environ["MXNET_DEVICE_PREFETCH"] = prev

    print(f"\ninput-pipeline overlap phase (depth-{depth} device ring, "
          f"pre-sharded placement):")
    print(f"  host input            : {input_ms:8.2f} ms/batch")
    print(f"  step, synchronous feed: {sync_ms:8.2f} ms/step  "
          f"(input + H2D + compute serialized)")
    print(f"  step, device prefetch : {overlap_ms:8.2f} ms/step  "
          f"({sync_ms / overlap_ms:.2f}x; ideal = max(input, compute) = "
          f"{max(input_ms, sync_ms - input_ms):.2f})")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?",
                    choices=["resnet", "bert", "gpt", "transformer"])
    ap.add_argument("--bs", type=int, default=0)
    ap.add_argument("--by", default="tf_op",
                    choices=["tf_op", "name", "category", "source"])
    ap.add_argument("--limit", type=int, default=40)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--no-opt-phase", action="store_true",
                    help="skip the imperative optimizer-apply phase row")
    ap.add_argument("--no-input-phase", action="store_true",
                    help="skip the input-pipeline / H2D overlap phase rows")
    ap.add_argument("--no-fused-step-phase", action="store_true",
                    help="skip the fused-step phase rows")
    ap.add_argument("--no-checkpoint-phase", action="store_true",
                    help="skip the checkpoint save-stall phase rows")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fused-step phase rows only (tier-1 gate: "
                         "no model build, no trace, runs on CPU in "
                         "seconds)")
    args = ap.parse_args()

    # memory columns for the phase rows: every compile event this run
    # triggers carries memory_analysis fields (one extra AOT compile
    # per program, warm-up only — cheap at the smoke's toy sizes too)
    os.environ.setdefault("MXNET_TELEMETRY_MEM", "1")

    if args.smoke:
        rows = profile_fused_step(smoke=True)
        # the smoke gate checks the mechanism, not the speedup (CPU
        # timing at toy sizes is noise): every fused row must be exactly
        # one executable dispatch per step
        assert all(d == 1 for m, d, _ in rows if m.startswith("fused"))
        ck = profile_checkpoint(smoke=True)
        # async stall must be measured and strictly the snapshot side:
        # the async arm's save() wall is bounded by the sync arm's
        # (snapshot + atomic write) on any platform
        ck = {m: (step, stall) for m, step, stall in ck}
        assert ck["async-save"][1] > 0.0
        assert ck["async-save"][1] <= ck["sync-save"][1] * 1.5 + 5.0, ck
        assert train_step_op_count_smoke() > 0
        return 0
    if args.model is None:
        ap.error("model is required unless --smoke")

    import jax

    from mxnet_tpu import profiler_xla

    bs = args.bs or {"resnet": 256, "bert": 64, "gpt": 4,
                     "transformer": 32}[args.model]
    trainer, x, y = {"resnet": build_resnet, "bert": build_bert,
                     "gpt": build_gpt,
                     "transformer": build_transformer}[args.model](bs)

    def run():
        return trainer.step(x, y)

    # compile + warmup
    loss = run()
    print("warmup loss:", float(onp.asarray(loss.asnumpy()).reshape(-1)[0]))
    run()

    # static sequencer-overhead metric beside the measured trace: the
    # compiled step's HLO instruction count (BASELINE.md round-3 anatomy
    # — the BERT step's wall-vs-device MFU gap is ~5,300 ops x ~1 us of
    # fixed per-op cost; the stacked-scan decode attacks the same class
    # of overhead on the decode side)
    print(f"train-step HLO op count: {trainer.step_hlo_op_count(x, y)}")

    import tempfile
    td = tempfile.mkdtemp(prefix="mxtpu_step_prof_")
    jax.profiler.start_trace(td)
    out = None
    for _ in range(args.iters):
        out = run()
    onp.asarray(out.asnumpy())  # readback sync through the tunnel
    jax.profiler.stop_trace()

    records = profiler_xla.parse_trace(td)
    for r in records:
        r["dur_us"] /= args.iters
        r["flops"] //= args.iters
        r["bytes"] //= args.iters
    rows = profiler_xla.aggregate(records, by=args.by)
    tot_us = sum(r["dur_us"] for r in rows)
    tot_fl = sum(r["flops"] for r in rows)
    if tot_us > 0:
        print(f"\ndevice step time: {tot_us / 1e3:.2f} ms   "
              f"model TFLOP: {tot_fl / 1e12:.3f}   "
              f"achieved {tot_fl / tot_us / 1e6:.1f} TFLOP/s "
              f"({100 * tot_fl / tot_us / 1e6 / PEAK_TFLOPS:.1f}% MFU)\n")
        print(profiler_xla.format_table(rows, peak_tflops=PEAK_TFLOPS,
                                        limit=args.limit))
    else:
        print("\n(no device trace records — per-op table skipped; "
              "phase rows below still measured)")
    if not args.no_input_phase:
        profile_input_overlap(trainer, x, y)
    if not args.no_opt_phase:
        profile_optimizer_apply(trainer)
    if not args.no_fused_step_phase:
        profile_fused_step()
    if not args.no_checkpoint_phase:
        profile_checkpoint()
    return 0


if __name__ == "__main__":
    sys.exit(main())
