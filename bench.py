"""Headline benchmark: BERT-base pretrain-style train step, tokens/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): upstream-MXNet-era BERT-base pretrain throughput on
V100 fp16 was ~10-20k tokens/sec/GPU; vs_baseline is measured against the
15k midpoint.  The model here is BERT-base geometry (12 layers, 768 units,
12 heads, seq 128) in bfloat16 with a full-vocab tied MLM head, trained by
the fused SPMD step (forward+backward+AdamW in one donated jit).
"""
import json
import os
import sys
import time

BASELINE_TOKENS_PER_SEC = 15000.0


def main():
    if os.environ.get("MXNET_BENCH_PLATFORM"):
        import jax
        jax.config.update("jax_platforms",
                          os.environ["MXNET_BENCH_PLATFORM"])
    import numpy as onp
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import BERTModel, BERTConfig

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    mx.random.seed(0)

    seq = 128
    batch = 64 if on_tpu else 8
    cfg = BERTConfig(vocab_size=30528, max_length=seq, num_layers=12,
                     units=768, num_heads=12, hidden_size=3072,
                     dtype="bfloat16" if on_tpu else "float32")
    if not on_tpu:  # CPU smoke config (local sanity runs only)
        cfg.num_layers = 2
    bert = BERTModel(cfg, use_pooler=False, use_mlm=True)

    class _MLMHeadOnly(gluon.Block):
        """Select the MLM logits as the training output."""

        def __init__(self):
            super().__init__()
            self.bert = bert

        def forward(self, tokens):
            return self.bert(tokens)[-1]

    net = _MLMHeadOnly()
    net.initialize(mx.init.Normal(0.02))

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = parallel.SPMDTrainer(net, loss_fn, "adamw",
                                   {"learning_rate": 1e-4}, mesh=mesh)

    rng = onp.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (batch, seq))
    labels = rng.randint(0, cfg.vocab_size, (batch, seq))
    data = mx.nd.array(toks)
    label = mx.nd.array(labels)

    # warmup (compile) + steady-state timing.  NOTE: timing must end with a
    # device->host readback (asnumpy) — on remote-tunneled TPU backends
    # block_until_ready returns before execution finishes, so a readback is
    # the only reliable synchronization point.  The timed region runs N
    # steps in ONE dispatch (lax.scan inside the jit) so host/tunnel
    # latency doesn't pollute the device-throughput measurement.
    for _ in range(2):
        float(onp.asarray(trainer.step(data, label).asnumpy()).reshape(()))
    n_steps = 20 if on_tpu else 4
    steps_data = mx.nd.array(onp.broadcast_to(toks, (n_steps,) + toks.shape))
    steps_label = mx.nd.array(onp.broadcast_to(labels,
                                               (n_steps,) + labels.shape))
    # compile the multi-step program outside the timed region
    float(onp.asarray(trainer.run_steps(
        steps_data, steps_label).asnumpy()).reshape(-1)[0])
    t0 = time.perf_counter()
    losses = trainer.run_steps(steps_data, steps_label)
    float(onp.asarray(losses.asnumpy()).reshape(-1)[-1])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * n_steps / dt / max(
        1, len(jax.devices()))
    print(json.dumps({
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
