"""Headline benchmark: BERT-base pretrain-style train step, tokens/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "platform",
"degraded"} — ALWAYS, under any backend condition (VERDICT r1 item 1: the
round-1 bench crashed at backend init and recorded nothing).

Architecture: the module re-execs itself as a subprocess for the actual
measurement (``_MXNET_BENCH_INNER=1``).  The outer orchestrator retries the
preferred backend with backoff, enforces a wall-clock timeout (a hung TPU
tunnel cannot wedge the bench), falls back to CPU, and if everything fails
still emits the JSON line with ``"degraded": true`` and an ``"error"``
field, exiting 0.

Baseline (BASELINE.md): upstream-MXNet-era BERT-base pretrain throughput on
V100 fp16 was ~10-20k tokens/sec/GPU; vs_baseline is measured against the
15k midpoint.  The model here is BERT-base geometry (12 layers, 768 units,
12 heads, seq 128) in bfloat16 with a full-vocab tied MLM head, trained by
the fused SPMD step (forward+backward+AdamW in one donated jit).
"""
import json
import os
import subprocess
import sys
import time

BASELINE_TOKENS_PER_SEC = 15000.0
METRIC = "bert_base_tokens_per_sec_per_chip"
UNIT = "tokens/sec/chip"

# wall-clock budget for one inner attempt (compile ~40s + 3 timed runs)
_INNER_TIMEOUT_S = int(os.environ.get("MXNET_BENCH_TIMEOUT", "1500"))


def _emit(value, platform, degraded, error=None):
    line = {
        "metric": METRIC,
        "value": round(float(value), 1),
        "unit": UNIT,
        "vs_baseline": round(float(value) / BASELINE_TOKENS_PER_SEC, 3),
        "platform": platform,
        "degraded": bool(degraded),
    }
    if error:
        line["error"] = str(error)[:300]
    print(json.dumps(line))
    sys.stdout.flush()


# --------------------------------------------------------------------------- #
# inner: the actual measurement (may crash / hang; the outer shields it)
# --------------------------------------------------------------------------- #

def _inner():
    import numpy as onp
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.models import BERTModel, BERTConfig

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    mx.random.seed(0)

    seq = 128
    batch = 64 if on_tpu else 8
    cfg = BERTConfig(vocab_size=30528, max_length=seq, num_layers=12,
                     units=768, num_heads=12, hidden_size=3072,
                     dtype="bfloat16" if on_tpu else "float32")
    if not on_tpu:  # CPU smoke config (degraded-mode runs)
        cfg.num_layers = 2
    bert = BERTModel(cfg, use_pooler=False, use_mlm=True)

    class _MLMHeadOnly(gluon.Block):
        """Select the MLM logits as the training output."""

        def __init__(self):
            super().__init__()
            self.bert = bert

        def forward(self, tokens):
            return self.bert(tokens)[-1]

    net = _MLMHeadOnly()
    net.initialize(mx.init.Normal(0.02))

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = parallel.SPMDTrainer(net, loss_fn, "adamw",
                                   {"learning_rate": 1e-4}, mesh=mesh)

    rng = onp.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (batch, seq))
    labels = rng.randint(0, cfg.vocab_size, (batch, seq))
    data = mx.nd.array(toks)
    label = mx.nd.array(labels)

    # warmup (compile) + steady-state timing.  NOTE: timing must end with a
    # device->host readback (asnumpy) — on remote-tunneled TPU backends
    # block_until_ready returns before execution finishes, so a readback is
    # the only reliable synchronization point.  The timed region runs N
    # steps in ONE dispatch (lax.scan inside the jit) so host/tunnel
    # latency doesn't pollute the device-throughput measurement.
    for _ in range(2):
        float(onp.asarray(trainer.step(data, label).asnumpy()).reshape(()))
    # 60 steps per dispatch: the remote-tunnel RTT (~0.1 s per call) is a
    # fixed cost — at 20 steps it still cost ~5 ms/step of phantom wall
    # time (measured r4: N=20 -> 50.8 ms/step, N=60 -> 45.2 ms/step, vs
    # 43.6 ms device time from the per-op profile)
    n_steps = 60 if on_tpu else 4
    # one h2d transfer + device-side broadcast (tunnel is ~33 MB/s)
    import jax.numpy as jnp
    steps_data = mx.nd.from_jax(jnp.broadcast_to(
        jnp.asarray(toks), (n_steps,) + toks.shape))
    steps_label = mx.nd.from_jax(jnp.broadcast_to(
        jnp.asarray(labels), (n_steps,) + labels.shape))
    # compile the multi-step program outside the timed region
    float(onp.asarray(trainer.run_steps(
        steps_data, steps_label).asnumpy()).reshape(-1)[0])
    best_dt = None
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        losses = trainer.run_steps(steps_data, steps_label)
        float(onp.asarray(losses.asnumpy()).reshape(-1)[-1])
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    tokens_per_sec = batch * seq * n_steps / best_dt / max(
        1, len(jax.devices()))
    degraded = os.environ.get("_MXNET_BENCH_DEGRADED") == "1" or (
        os.environ.get("_MXNET_BENCH_WANTED_TPU") == "1" and not on_tpu)
    _emit(tokens_per_sec, platform, degraded=degraded)
    return 0


# --------------------------------------------------------------------------- #
# outer: orchestration — probe, retry with backoff, CPU fallback
# --------------------------------------------------------------------------- #

def _run_attempt(platform):
    """Run the inner benchmark in a subprocess; return (ok, stdout, err)."""
    env = os.environ.copy()
    env["_MXNET_BENCH_INNER"] = "1"
    if platform:
        env["JAX_PLATFORMS"] = platform
        if platform == "cpu" and env.get("_MXNET_BENCH_WANTED_TPU"):
            env["_MXNET_BENCH_DEGRADED"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=_INNER_TIMEOUT_S,
            env=env)
    except subprocess.TimeoutExpired:
        return False, "", f"timeout after {_INNER_TIMEOUT_S}s"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return False, proc.stdout, f"rc={proc.returncode}: {' | '.join(tail)}"
    return True, proc.stdout, None


def _relay_json(stdout):
    """Find and re-print the inner JSON line; True if found."""
    for ln in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(ln)
        except ValueError:
            continue
        if isinstance(parsed, dict) and parsed.get("metric") == METRIC:
            print(ln)
            sys.stdout.flush()
            return True
    return False


def main():
    if os.environ.get("_MXNET_BENCH_INNER") == "1":
        return _inner()

    preferred = os.environ.get("MXNET_BENCH_PLATFORM", "")
    if preferred:
        plan = [(preferred, 0), (preferred, 10)]
        if preferred != "cpu":
            os.environ["_MXNET_BENCH_WANTED_TPU"] = "1"
            plan.append(("cpu", 0))
    else:
        # default: let jax pick (tpu if the tunnel is up) with retries,
        # then force-CPU as the degraded fallback
        os.environ["_MXNET_BENCH_WANTED_TPU"] = "1"
        plan = [("", 0), ("", 15), ("", 30), ("cpu", 0)]

    last_err = None
    for platform, backoff in plan:
        if backoff:
            time.sleep(backoff)
        ok, stdout, err = _run_attempt(platform)
        if ok and _relay_json(stdout):
            return 0
        last_err = err or "inner produced no JSON line"
    _emit(0.0, "none", degraded=True, error=last_err)
    return 0  # the JSON line IS the result; never fail the driver run


if __name__ == "__main__":
    sys.exit(main())
