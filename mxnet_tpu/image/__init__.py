"""``mx.image`` — image decode, resize/crop/color augmenters, ImageIter.

Reference surface: ``python/mxnet/image/image.py`` + ``image/detection.py``
(SURVEY.md §3.2 "io / recordio / image" row: "imdecode via C++ op, ImageIter
python-side pipeline, detection augmenters").

TPU-native stance: decode happens on the HOST (PIL-backed here; the native
C++ pipeline covers the throughput path), augmentation math is numpy on host
— device time is reserved for the model step, and batches land on device via
``mx.nd.array`` once, already augmented.  This mirrors the reference, where
decode+augment run in the C++ OMP pool and only batches reach the GPU.
"""
from .image import (imdecode, imdecode_np, imencode, imread, imresize,
                    resize_short, fixed_crop, center_crop, random_crop,
                    random_size_crop, color_normalize, HSVJitterAug,
                    Augmenter, ResizeAug, ForceResizeAug, RandomCropAug,
                    CenterCropAug, RandomSizedCropAug, HorizontalFlipAug,
                    CastAug, ColorNormalizeAug, BrightnessJitterAug,
                    ContrastJitterAug, SaturationJitterAug, LightingAug,
                    ColorJitterAug, CreateAugmenter, ImageIter)
from .detection import (DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
                        DetRandomCropAug, DetRandomPadAug, CreateDetAugmenter,
                        ImageDetIter)
