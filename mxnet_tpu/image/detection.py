"""Detection augmenters + ImageDetIter (reference
``python/mxnet/image/detection.py``; SURVEY.md §3.2 "detection augmenters").

Labels are ``(N, 5+) [class_id, xmin, ymin, xmax, ymax, ...]`` with
coordinates normalised to [0,1], the reference's SSD convention.
"""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray
from .image import (Augmenter, ImageIter, imdecode_np, _resize_np,
                    HorizontalFlipAug)


class DetAugmenter:
    """Detection augmenter: ``__call__(src, label) -> (src, label)``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return [self.__class__.__name__.lower(), self._kwargs]

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only :class:`Augmenter` for detection (label unchanged —
    only safe for color/cast augmenters)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter from a list (or skip)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            img = src.asnumpy()
            src = nd.array(img[:, ::-1].copy(), dtype=str(img.dtype))
            label = label.copy()
            valid = label[:, 0] >= 0
            xmin = 1.0 - label[valid, 3]
            xmax = 1.0 - label[valid, 1]
            label[valid, 1], label[valid, 3] = xmin, xmax
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop with IoU constraint against ground-truth boxes."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        img = src.asnumpy()
        h, w = img.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(w, int(round(onp.sqrt(area * w * h * ratio))))
            ch = min(h, int(round(onp.sqrt(area * w * h / ratio))))
            x0 = pyrandom.randint(0, w - cw)
            y0 = pyrandom.randint(0, h - ch)
            crop = (x0 / w, y0 / h, (x0 + cw) / w, (y0 + ch) / h)
            new_label = self._update_labels(label, crop)
            if new_label is not None:
                out = img[y0:y0 + ch, x0:x0 + cw]
                return nd.array(out.copy(), dtype=str(img.dtype)), new_label
        return src, label

    def _update_labels(self, label, crop):
        cx0, cy0, cx1, cy1 = crop
        out = []
        for row in label:
            if row[0] < 0:
                continue
            xmin, ymin, xmax, ymax = row[1:5]
            ixmin, iymin = max(xmin, cx0), max(ymin, cy0)
            ixmax, iymax = min(xmax, cx1), min(ymax, cy1)
            iw, ih = max(0.0, ixmax - ixmin), max(0.0, iymax - iymin)
            box_area = max(1e-12, (xmax - xmin) * (ymax - ymin))
            if iw * ih / box_area < self.min_object_covered:
                continue
            nw, nh = cx1 - cx0, cy1 - cy0
            new = row.copy()
            new[1] = (ixmin - cx0) / nw
            new[2] = (iymin - cy0) / nh
            new[3] = (ixmax - cx0) / nw
            new[4] = (iymax - cy0) / nh
            out.append(new)
        if not out:
            return None
        return onp.stack(out)


class DetRandomPadAug(DetAugmenter):
    """Randomly pad (zoom out) with fill value, rescaling boxes."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(area_range=area_range, pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = src.asnumpy()
        h, w, c = img.shape
        scale = pyrandom.uniform(*self.area_range)
        if scale <= 1.0:
            return src, label
        nw, nh = int(w * onp.sqrt(scale)), int(h * onp.sqrt(scale))
        x0 = pyrandom.randint(0, nw - w)
        y0 = pyrandom.randint(0, nh - h)
        canvas = onp.empty((nh, nw, c), dtype=img.dtype)
        canvas[...] = onp.asarray(self.pad_val, dtype=img.dtype)[:c]
        canvas[y0:y0 + h, x0:x0 + w] = img
        label = label.copy()
        valid = label[:, 0] >= 0
        label[valid, 1] = (label[valid, 1] * w + x0) / nw
        label[valid, 2] = (label[valid, 2] * h + y0) / nh
        label[valid, 3] = (label[valid, 3] * w + x0) / nw
        label[valid, 4] = (label[valid, 4] * h + y0) / nh
        return nd.array(canvas, dtype=str(img.dtype)), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), pad_val=(127, 127, 127),
                       inter_method=2, **kwargs):
    """Build the standard detection augmenter list (reference
    ``CreateDetAugmenter``)."""
    from .image import (CastAug, ColorNormalizeAug, HSVJitterAug,
                        ForceResizeAug)
    auglist = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])))
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), 50, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2], data_shape[1]),
                                               inter_method)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(HSVJitterAug(brightness, contrast,
                                                 saturation)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53], dtype=onp.float32)
    if std is True:
        std = onp.array([58.395, 57.12, 57.375], dtype=onp.float32)
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: labels are padded ``(batch, max_objects, 5)``
    tensors (reference ``mx.image.ImageDetIter``)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglst=None, path_root=None, shuffle=False,
                 aug_list=None, data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglst=path_imglst,
                         path_root=path_root, shuffle=shuffle, aug_list=[],
                         data_name=data_name, label_name=label_name,
                         last_batch_handle=last_batch_handle)
        self.det_auglist = aug_list
        self._label_shape = None

    @staticmethod
    def _parse_label(raw):
        """Reference label layout: [header_width, obj_width, ...objects]."""
        raw = onp.asarray(raw, dtype=onp.float32).reshape(-1)
        if raw.size < 2:
            raise MXNetError("invalid detection label")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        body = raw[header_width:]
        n = body.size // obj_width
        return body[:n * obj_width].reshape(n, obj_width)

    def next(self):
        from ..io import DataBatch
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, h, w, c), dtype=onp.float32)
        labels = []
        i = 0
        try:
            while i < self.batch_size:
                raw_label, s = self.next_sample()
                img = nd.array(imdecode_np(s), dtype="uint8")
                label = self._parse_label(raw_label)
                for aug in self.det_auglist:
                    img, label = aug(img, label)
                arr = img.asnumpy()
                if arr.shape[:2] != (h, w):
                    arr = _resize_np(arr.astype(onp.uint8), w, h)
                batch_data[i] = arr.astype(onp.float32)
                labels.append(label)
                i += 1
        except StopIteration:
            if i == 0:
                raise
        max_obj = max((l.shape[0] for l in labels), default=1)
        obj_w = labels[0].shape[1] if labels else 5
        batch_label = onp.full((self.batch_size, max_obj, obj_w), -1.0,
                               dtype=onp.float32)
        for j, l in enumerate(labels):
            batch_label[j, :l.shape[0]] = l
        data = nd.array(batch_data.transpose(0, 3, 1, 2), dtype=self.dtype)
        return DataBatch(data=[data], label=[nd.array(batch_label)],
                         pad=self.batch_size - i)
