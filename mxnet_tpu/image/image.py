"""Image decode + augmenters + ImageIter (reference
``python/mxnet/image/image.py``; SURVEY.md §3.2, §4.5).

Decode uses OpenCV when available (the reference's backend) and falls back
to PIL.  All augmenters operate on HWC uint8/float32 numpy arrays on the
host; ``ImageIter`` assembles NCHW/NHWC device batches.
"""
from __future__ import annotations

import io as _pyio
import logging
import random as pyrandom

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray

try:
    import cv2 as _cv2
except Exception:  # pragma: no cover
    _cv2 = None

try:
    from PIL import Image as _PILImage
except Exception:  # pragma: no cover
    _PILImage = None


# --------------------------------------------------------------------- #
# decode / encode / resize primitives
# --------------------------------------------------------------------- #
def imdecode_np(buf: bytes, iscolor: int = 1, to_rgb: bool = True) -> onp.ndarray:
    """Decode an encoded image to an HWC uint8 numpy array (RGB order when
    ``to_rgb``, matching the reference's ``mx.image.imdecode`` default).

    Backend order: OpenCV → native libjpeg (mxtpu_io) → PIL."""
    if _cv2 is None and len(buf) > 2 and buf[:2] == b"\xff\xd8":
        from .. import _native
        if _native.available():
            try:
                return _native.decode_jpeg(bytes(buf), want_color=iscolor != 0)
            except Exception:
                pass  # fall through to PIL on corrupt/unsupported streams
    if _cv2 is not None:
        flag = _cv2.IMREAD_COLOR if iscolor != 0 else _cv2.IMREAD_GRAYSCALE
        img = _cv2.imdecode(onp.frombuffer(buf, dtype=onp.uint8), flag)
        if img is None:
            raise MXNetError("imdecode failed")
        if img.ndim == 2:
            img = img[:, :, None]
        elif to_rgb:
            img = _cv2.cvtColor(img, _cv2.COLOR_BGR2RGB)
        return img
    if _PILImage is None:
        raise MXNetError("imdecode needs cv2 or PIL")
    img = _PILImage.open(_pyio.BytesIO(buf))
    img = img.convert("L" if iscolor == 0 else "RGB")
    arr = onp.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def imdecode(buf, iscolor: int = 1, to_rgb: bool = True, **kwargs) -> NDArray:
    """``mx.image.imdecode`` — decode to an ``NDArray`` (HWC uint8)."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    return nd.array(imdecode_np(bytes(buf), iscolor=iscolor, to_rgb=to_rgb),
                    dtype="uint8")


def imencode(img, quality: int = 95, img_fmt: str = ".jpg") -> bytes:
    """Encode an HWC uint8 array to JPEG/PNG bytes."""
    if isinstance(img, NDArray):
        img = img.asnumpy()
    img = onp.ascontiguousarray(img, dtype=onp.uint8)
    if _cv2 is not None:
        bgr = _cv2.cvtColor(img, _cv2.COLOR_RGB2BGR) if img.shape[-1] == 3 else img
        params = [_cv2.IMWRITE_JPEG_QUALITY, quality] if "jp" in img_fmt else []
        ok, enc = _cv2.imencode(img_fmt, bgr, params)
        if not ok:
            raise MXNetError("imencode failed")
        return enc.tobytes()
    if _PILImage is None:
        raise MXNetError("imencode needs cv2 or PIL")
    fmt = "JPEG" if "jp" in img_fmt.lower() else img_fmt.strip(".").upper()
    b = _pyio.BytesIO()
    _PILImage.fromarray(img.squeeze() if img.shape[-1] == 1 else img).save(
        b, format=fmt, quality=quality)
    return b.getvalue()


def imread(filename: str, iscolor: int = 1, to_rgb: bool = True) -> NDArray:
    with open(filename, "rb") as f:
        return imdecode(f.read(), iscolor=iscolor, to_rgb=to_rgb)


def _resize_np(img: onp.ndarray, w: int, h: int, interp=1) -> onp.ndarray:
    if _cv2 is not None:
        interps = {0: _cv2.INTER_NEAREST, 1: _cv2.INTER_LINEAR,
                   2: _cv2.INTER_CUBIC, 3: _cv2.INTER_AREA,
                   4: _cv2.INTER_LANCZOS4}
        out = _cv2.resize(img, (w, h), interpolation=interps.get(interp, 1))
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    pil = _PILImage.fromarray(img.squeeze() if img.shape[-1] == 1 else img)
    out = onp.asarray(pil.resize((w, h),
                                 _PILImage.NEAREST if interp == 0 else _PILImage.BILINEAR))
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    return nd.array(_resize_np(img, w, h, interp), dtype=str(img.dtype))


def resize_short(src, size: int, interp: int = 1) -> NDArray:
    """Resize so the SHORTER edge equals ``size`` (aspect preserved)."""
    img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return nd.array(_resize_np(img, new_w, new_h, interp), dtype=str(img.dtype))


def fixed_crop(src, x0: int, y0: int, w: int, h: int, size=None, interp: int = 1):
    img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize_np(out, size[0], size[1], interp)
    return nd.array(out, dtype=str(img.dtype))


def center_crop(src, size, interp: int = 1):
    img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0 = max((w - cw) // 2, 0)
    y0 = max((h - ch) // 2, 0)
    cw, ch = min(cw, w), min(ch, h)
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def random_crop(src, size, interp: int = 1):
    img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    h, w = img.shape[:2]
    cw, ch = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - cw)
    y0 = pyrandom.randint(0, h - ch)
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def random_size_crop(src, size, area, ratio, interp: int = 1):
    """Random area-and-aspect crop (the Inception-style augmentation)."""
    img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(pyrandom.uniform(*log_ratio))
        cw = int(round(onp.sqrt(target_area * aspect)))
        ch = int(round(onp.sqrt(target_area / aspect)))
        if cw <= w and ch <= h:
            x0 = pyrandom.randint(0, w - cw)
            y0 = pyrandom.randint(0, h - ch)
            return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    src = src.astype("float32") if isinstance(src, NDArray) else nd.array(src, dtype="float32")
    out = src - (mean if isinstance(mean, NDArray) else nd.array(onp.asarray(mean, dtype=onp.float32)))
    if std is not None:
        out = out / (std if isinstance(std, NDArray) else nd.array(onp.asarray(std, dtype=onp.float32)))
    return out


# --------------------------------------------------------------------- #
# Augmenter classes (reference: Augmenter hierarchy in image.py)
# --------------------------------------------------------------------- #
class Augmenter:
    """Image augmenter base; ``__call__(src: NDArray) -> NDArray``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return [self.__class__.__name__.lower(), self._kwargs]

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=1):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            img = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
            return nd.array(img[:, ::-1].copy(), dtype=str(img.dtype))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return src.astype("float32") * alpha


class ContrastJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], dtype=onp.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        img = src.asnumpy().astype(onp.float32)
        gray = (img * self._coef).sum() * (3.0 / img.size)
        return nd.array(img * alpha + gray * (1 - alpha), dtype="float32")


class SaturationJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], dtype=onp.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        img = src.asnumpy().astype(onp.float32)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return nd.array(img * alpha + gray * (1 - alpha), dtype="float32")


class HSVJitterAug(Augmenter):
    """Combined brightness/contrast/saturation jitter in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


ColorJitterAug = HSVJitterAug


class LightingAug(Augmenter):
    """PCA-based RGB lighting noise (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, dtype=onp.float32)
        self.eigvec = onp.asarray(eigvec, dtype=onp.float32)

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, size=(3,)).astype(onp.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return src.astype("float32") + nd.array(rgb)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Build the standard augmenter list (reference ``CreateAugmenter``)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(HSVJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53], dtype=onp.float32)
    if std is True:
        std = onp.array([58.395, 57.12, 57.375], dtype=onp.float32)
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# --------------------------------------------------------------------- #
# ImageIter — python-side decode+augment pipeline over .rec or .lst
# --------------------------------------------------------------------- #
class ImageIter:
    """Image data iterator reading RecordIO (``path_imgrec``) or an image
    list (``path_imglst`` + ``path_root``); reference ``mx.image.ImageIter``
    (SURVEY.md §4.5).  Yields ``DataBatch`` with NCHW float data."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglst=None, path_root=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 dtype="float32", last_batch_handle="pad", **kwargs):
        from ..io import DataDesc
        from .. import recordio as rio
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        self.last_batch_handle = last_batch_handle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_resize", "rand_mirror",
                                                    "mean", "std", "brightness",
                                                    "contrast", "saturation",
                                                    "pca_noise", "inter_method")})
        self.provide_data = [DataDesc(data_name, (batch_size,) + self.data_shape, dtype)]
        lshape = (batch_size,) if label_width == 1 else (batch_size, label_width)
        self.provide_label = [DataDesc(label_name, lshape, "float32")]

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = path_imgrec[:-4] + ".idx"
            import os as _os
            if _os.path.isfile(idx_path):
                self.imgrec = rio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = rio.MXRecordIO(path_imgrec, "r")
        elif path_imglst or imglist is not None:
            self.imglist = {}
            if imglist is not None:
                for i, (label, fname) in enumerate(imglist):
                    self.imglist[i] = (onp.asarray(label, dtype=onp.float32), fname)
            else:
                with open(path_imglst) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        key = int(parts[0])
                        label = onp.asarray([float(x) for x in parts[1:-1]],
                                            dtype=onp.float32)
                        self.imglist[key] = (label, parts[-1])
            self.seq = list(self.imglist.keys())
            self.path_root = path_root or "."
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglst, or imglist")
        if self.seq is not None and num_parts > 1:
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]
        self.cur = 0
        self._cache = None
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        from .. import recordio as rio
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = rio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            import os as _os
            with open(_os.path.join(self.path_root, fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = rio.unpack(s)
        return header.label, img

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from ..io import DataBatch
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, h, w, c), dtype=onp.float32)
        batch_label = onp.zeros((self.batch_size, self.label_width), dtype=onp.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, s = self.next_sample()
                img = nd.array(imdecode_np(s), dtype="uint8")
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy()
                if arr.shape[:2] != (h, w):
                    arr = _resize_np(arr.astype(onp.uint8), w, h)
                batch_data[i] = arr.astype(onp.float32)
                batch_label[i] = onp.asarray(label, dtype=onp.float32).reshape(-1)[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            if self.last_batch_handle == "discard":
                raise
        # NCHW for the model (reference layout)
        data = nd.array(batch_data.transpose(0, 3, 1, 2), dtype=self.dtype)
        label = nd.array(batch_label[:, 0] if self.label_width == 1 else batch_label)
        return DataBatch(data=[data], label=[label], pad=self.batch_size - i)
