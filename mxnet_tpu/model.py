"""Model checkpoint helpers shared by Module & Trainer.

Reference surface: ``python/mxnet/model.py`` (SURVEY.md §3.2 "model.py
helpers" row): ``save_checkpoint/load_checkpoint`` (``prefix-symbol.json`` +
``prefix-%04d.params``), ``_create_kvstore``.
"""
from __future__ import annotations

import logging
import os

from .base import MXNetError
from . import ndarray as nd

BatchEndParam = None  # set below


class _BatchEndParam(tuple):
    pass


try:
    from collections import namedtuple
    BatchEndParam = namedtuple("BatchEndParam",
                               ["epoch", "nbatch", "eval_metric", "locals"])
except Exception:  # pragma: no cover
    pass


def _create_kvstore(kvstore, num_device, arg_params):
    """Normalize a kvstore spec into (kvstore, update_on_kvstore)
    (reference ``_create_kvstore``)."""
    from .kvstore import KVStore, create as kv_create
    update_on_kvstore = bool(int(os.environ.get(
        "MXNET_UPDATE_ON_KVSTORE", "1")))
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kv_create(kvstore)
    else:
        raise MXNetError(f"invalid kvstore {kvstore!r}")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save ``prefix-symbol.json`` (if a symbol is given) +
    ``prefix-%04d.params`` (reference ``save_checkpoint``)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json",
                    remove_amp_cast=remove_amp_cast)
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    nd.save(param_name, save_dict)
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_params_file(param_file):
    """Split a ``.params`` file into (arg, aux) dicts — the single
    implementation of the ``arg:``/``aux:`` key scheme."""
    loaded = nd.load(param_file)
    if isinstance(loaded, list):
        raise MXNetError("params file has unnamed arrays; cannot map")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:  # plain name->array file (gluon save_parameters)
            arg_params[k] = v
    return arg_params, aux_params


def load_params(prefix, epoch):
    """→ (arg_params, aux_params) from ``prefix-%04d.params``."""
    return load_params_file(f"{prefix}-{epoch:04d}.params")


def load_checkpoint(prefix, epoch):
    """→ (symbol_or_None, arg_params, aux_params) (reference
    ``load_checkpoint``)."""
    sym_file = f"{prefix}-symbol.json"
    symbol = None
    if os.path.isfile(sym_file):
        from .symbol import load as sym_load
        symbol = sym_load(sym_file)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
