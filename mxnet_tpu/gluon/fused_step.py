"""Fused train step: ONE donated-buffer XLA executable per step, with
on-device gradient accumulation.

Reference counterpart (SURVEY.md §4.2, §7): the reference amortizes
per-op overhead by amalgamating the training step behind CachedOp + the
dependency engine.  Our imperative port still ran the step as a
Python-sequenced phase chain — jitted CachedOp forward, tape-driven
backward, kvstore allreduce, fused ``Optimizer.multi_update`` — with
host round-trips between each phase.  ``FusedStep`` collapses the chain:
forward + loss + backward (``autograd.trace_value_and_grad`` — no tape)
+ grad rescale + cross-replica reduction (GSPMD, from input shardings) +
the optimizer apply (``Optimizer.fused_step_apply``) trace into one
``jax.jit`` executable with DONATED weight / optimizer-state /
grad-accumulator buffers, keyed by (batch shape/dtype signature, phase,
training flag, optimizer hyperparameters).

Gradient accumulation folds into the same executable:
``Trainer(update_interval=N)`` compiles TWO executables — a *micro* step
(forward+backward+accumulate into a device-resident accumulator ring)
and an *apply* step (accumulate + optimizer apply + accumulator reset) —
and fires the apply only every Nth call, with the 1/(N·batch) rescale
riding the apply's existing rescale operand.  A large effective batch
pays ONE optimizer apply and ONE replica sync per window instead of N.

``MXNET_FUSED_STEP=0`` (or an unsupported configuration: kvstore-backed
reduction, per-ctx replicas, sparse params, non-fusable optimizers like
SGLD) restores today's phase-by-phase path — record → tape backward →
``Trainer.step`` — bit-for-bit.
"""
from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp

from .. import telemetry
from ..base import MXNetError

__all__ = ["FusedStep", "fused_step_enabled", "step_counters",
           "reset_step_counters"]

# Dispatch accounting (read by the dispatch-count regression test and the
# fused-step benchmark rows):
#   dispatches        — fused-step executable invocations (exactly one per
#                       fused_step() call on the fused path)
#   micro_dispatches  — accumulate-only invocations (mid-window)
#   apply_dispatches  — invocations that ran the optimizer apply (one per
#                       update interval)
#   legacy_steps      — calls that took the phase-by-phase fallback
#   compiles          — executable cache misses (traces)
step_counters = {"dispatches": 0, "micro_dispatches": 0,
                 "apply_dispatches": 0, "legacy_steps": 0, "compiles": 0}


def reset_step_counters():
    for k in step_counters:
        step_counters[k] = 0


# registry instruments mirroring the dict above (plus step latency /
# accumulation-window phase), created on first use — module import must
# not touch the registry
_tele = None


def _instruments():
    global _tele
    if _tele is None:
        _tele = {
            "lat_micro": telemetry.histogram("fused_step_seconds",
                                             phase="micro"),
            "lat_apply": telemetry.histogram("fused_step_seconds",
                                             phase="apply"),
            "d_micro": telemetry.counter("fused_step_dispatches_total",
                                         phase="micro"),
            "d_apply": telemetry.counter("fused_step_dispatches_total",
                                         phase="apply"),
            "d_legacy": telemetry.counter("fused_step_dispatches_total",
                                          phase="legacy"),
            "window": telemetry.gauge("fused_step_window_pos"),
        }
    return _tele


def fused_step_enabled() -> bool:
    """Escape hatch: ``MXNET_FUSED_STEP=0`` restores the phase-by-phase
    step (read per call so tests can toggle it)."""
    return os.environ.get("MXNET_FUSED_STEP", "1") != "0"


class FusedStep:
    """Step compiler for one ``(Trainer, loss_fn)`` pair.

    ``loss_fn(*batch)`` is NDArray-level user code returning the
    per-sample loss (or a ``(loss, *extras)`` tuple — extras such as
    predictions ride through the executable undifferentiated).  Created
    and cached by ``Trainer.fused_step``; define the loss_fn ONCE outside
    the training loop so the cache key (``id(loss_fn)``) is stable.
    """

    def __init__(self, trainer, loss_fn, data_sharding=None,
                 train_mode=True):
        self._trainer = trainer
        self._loss_fn = loss_fn
        # optional NamedSharding for the batch operands (see
        # parallel.collectives.dp_sharding): placing the batch over the
        # data axis makes GSPMD insert the cross-replica grad all-reduce
        # INSIDE this executable — the kvstore phase folded into the step
        self._data_sharding = data_sharding
        self._train_mode = bool(train_mode)
        self._built = False
        self._train_idx: list = []     # trainer._params indices, live only
        self._train_params: list = []
        self._frozen_params: list = []
        self._mp_flags: list = []
        self._pure = None              # trace_value_and_grad closure
        self._cache: dict = {}         # (phase, sig, ...) -> jitted fn
        self._accum = None             # device grad accumulators (N > 1)
        self._accum_key = None         # train.grad_accum ledger key
        self._legacy_accum = None      # host-path accumulators (fallback)
        self._static_supported = None  # cached config verdict

    # ------------------------------------------------------------------ #
    def _supported(self) -> bool:
        # only the env hatch is re-read per call; the kvstore/replica/
        # sparse/optimizer facts are fixed once training starts, and an
        # O(n_params) scan per step would re-create exactly the per-param
        # host overhead the one-dispatch design removes
        if not fused_step_enabled():
            return False
        if self._static_supported is None:
            tr = self._trainer
            tr._init_kvstore()
            ok = not (tr._kvstore is not None or tr._update_on_kvstore)
            # SGLD: host RNG in the rule — not traceable once
            ok = ok and tr._optimizer._fusable
            # per-ctx replicas / sparse params: kvstore + per-param paths
            ok = ok and all(
                p._replicas is None and p._stype == "default"
                and p._grad_stype == "default" for p in tr._params)
            self._static_supported = ok
        return self._static_supported

    # ------------------------------------------------------------------ #
    def _build(self, nd_batch):
        from .. import autograd
        from .block import _no_hybrid

        tr = self._trainer
        if any(p._data is None for p in tr._params):
            # materialize deferred shapes with one imperative forward
            # (the _CachedOp._ensure_params discipline)
            with autograd.pause(train_mode=False), _no_hybrid():
                self._loss_fn(*nd_batch)
        for i, p in enumerate(tr._params):
            if p._data is None:
                raise MXNetError(
                    f"fused_step: parameter {p.name} is not initialized "
                    "after one forward; initialize() the block first")
            if p.grad_req == "null":
                self._frozen_params.append(p)
            else:
                tr._ensure_state(i)
                self._train_idx.append(i)
                self._train_params.append(p)
        opt = tr._optimizer
        self._mp_flags = [
            opt._use_mp(tr._params[i]._data._data, tr._states[i])
            for i in self._train_idx]
        self._pure = autograd.trace_value_and_grad(
            self._loss_fn, self._train_params, self._frozen_params,
            train_mode=self._train_mode)
        self._place_params()
        self._built = True
        self._trainer._account_params()

    def _place_params(self):
        """With a data-sharded batch (``data_sharding=``), weights /
        states must live on the SAME mesh or jit refuses the mixed
        committed placements: replicate them over the batch's mesh
        (params with their own ``set_sharding`` keep it).  GSPMD then
        compiles the cross-replica grad reduction into the step — this
        is the fused path's allreduce."""
        sh = self._data_sharding
        if sh is None or not hasattr(sh, "mesh"):
            return
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel.mesh import global_put

        tr = self._trainer
        repl = NamedSharding(sh.mesh, PartitionSpec())
        for p in self._train_params + self._frozen_params:
            tgt = p._sharding if p._sharding is not None else repl
            p._data._data = global_put(p._data._data, tgt)
        for i in self._train_idx:
            tr._states[i] = jax.tree.map(
                lambda a: global_put(a, repl)
                if hasattr(a, "shape") else a, tr._states[i])

    # ------------------------------------------------------------------ #
    def _get_fn(self, phase, sig):
        opt = self._trainer._optimizer
        key = (phase, sig, self._train_mode,
               self._trainer._update_interval > 1, opt._hyper_key(),
               opt.clip_gradient is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = telemetry.instrument_jit(
                self._compile(phase), "gluon.fused_step",
                key=(phase, sig), fields={"phase": phase})
            self._cache[key] = fn
            step_counters["compiles"] += 1
        return fn

    def _compile(self, phase):
        pure = self._pure
        opt = self._trainer._optimizer
        mp_flags = list(self._mp_flags)
        has_accum = self._trainer._update_interval > 1

        if phase == "micro":
            def micro(train_vals, frozen_vals, accum, key, *args):
                outs, grads, new_frozen = pure(key, train_vals,
                                               frozen_vals, *args)
                new_accum = [a + g.astype(a.dtype)
                             for a, g in zip(accum, grads)]
                return outs, new_accum, new_frozen

            # the accumulator ring is donated: accumulate is in-place at
            # the XLA level, weights/states pass through untouched
            return jax.jit(micro, donate_argnums=(2,))

        def apply(train_vals, opt_states, frozen_vals, accum, key, lrs,
                  wds, ts, rescale, *args):
            outs, grads, new_frozen = pure(key, train_vals, frozen_vals,
                                           *args)
            if has_accum:
                totals = [a + g.astype(a.dtype)
                          for a, g in zip(accum, grads)]
            else:
                totals = list(grads)
            new_ws, new_ss = opt.fused_step_apply(
                list(train_vals), totals, list(opt_states), mp_flags,
                lrs, wds, ts, rescale)
            new_accum = [jnp.zeros_like(a) for a in accum] if has_accum \
                else []
            return outs, new_ws, new_ss, new_frozen, new_accum

        donate = (0, 1, 3) if has_accum else (0, 1)
        return jax.jit(apply, donate_argnums=donate)

    # ------------------------------------------------------------------ #
    def __call__(self, batch, batch_size=None):
        from ..ndarray.ndarray import NDArray
        from .. import random as mxrandom
        from ..ndarray.ndarray import _grad_dtype

        tr = self._trainer
        nd_batch = [b if isinstance(b, NDArray) else NDArray(jnp.asarray(b))
                    for b in batch]
        if batch_size is None:
            batch_size = nd_batch[0].shape[0] if nd_batch[0].shape else 1
        if not self._supported():
            return self._legacy(nd_batch, batch_size)
        if not self._built:
            self._build(nd_batch)

        args = []
        if self._data_sharding is not None:
            # on a multi-process mesh each rank passes ITS batch slice
            # and global_put assembles the pod-global batch; the jitted
            # step then spans process boundaries (grad allreduce over
            # DCN) while staying one executable dispatch per rank
            from ..parallel.mesh import global_put

            for b in nd_batch:
                args.append(global_put(b._data, self._data_sharding))
        else:
            args = [b._data for b in nd_batch]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        N = tr._update_interval
        train_vals = [p._data._data for p in self._train_params]
        frozen_vals = [p._data._data for p in self._frozen_params]
        if self._data_sharding is not None \
                and hasattr(self._data_sharding, "mesh") \
                and jax.process_count() > 1:
            # pod discipline: EVERY operand of the global-mesh jit must
            # be a global array (keys, hypers, the accumulator ring) —
            # a process-local leftover turns the one-executable step
            # into a placement error
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel.mesh import global_put

            _repl = NamedSharding(self._data_sharding.mesh,
                                  PartitionSpec())

            def _g(a):
                return global_put(a, _repl)
        else:
            def _g(a):
                return a
        key = _g(mxrandom.next_key())
        if N > 1 and self._accum is None:
            adopted = self._adopt_pending_accum(tr, train_vals)
            self._accum = [_g(a) for a in adopted] if adopted else [
                _g(jnp.zeros(v.shape, _grad_dtype(v.dtype)))
                for v in train_vals]
            # the accumulator ring is a real device-resident cost of
            # update_interval>1 — one ledger entry PER FusedStep (a
            # trainer driving several loss_fns owns several rings, so
            # keying by trainer alone would overwrite), sized once
            # (the donated ring keeps these shapes every window)
            from ..telemetry.memory import ACCOUNTANT

            self._accum_key = \
                f"{self._trainer._mem_key()}:fs{id(self):x}"
            ACCOUNTANT.set("train.grad_accum", self._accum_key,
                           self._accum)

        tele = _instruments()
        tr._window_pos += 1
        if tr._window_pos < N:
            fn = self._get_fn("micro", sig)
            t0 = time.perf_counter()
            with telemetry.annotation("mx:fused_step:micro"):
                outs, self._accum, new_frozen = fn(
                    train_vals, frozen_vals, self._accum, key, *args)
            tele["lat_micro"].observe(time.perf_counter() - t0)
            tele["d_micro"].inc()
            tele["window"].set(tr._window_pos)
            step_counters["dispatches"] += 1
            step_counters["micro_dispatches"] += 1
            for p, v in zip(self._frozen_params, new_frozen):
                p._data._data = v
            return self._wrap_outs(outs)

        # window boundary: ONE executable runs fwd+bwd+accumulate+apply
        tr._window_pos = 0
        opt = tr._optimizer
        lrs, wds, ts = [], [], []
        for i in self._train_idx:
            opt._update_count(i)
            lrs.append(opt._get_lr(i))
            wds.append(opt._get_wd(i))
            ts.append(opt._index_update_count[i])
        rescale = jnp.float32(tr._scale / (float(batch_size) * N))
        states = [tr._states[i] for i in self._train_idx]
        fn = self._get_fn("apply", sig)
        t0 = time.perf_counter()
        with telemetry.annotation("mx:fused_step:apply"):
            outs, new_ws, new_ss, new_frozen, new_accum = fn(
                train_vals, states, frozen_vals,
                self._accum if N > 1 else [], key,
                _g(jnp.asarray(lrs, jnp.float32)),
                _g(jnp.asarray(wds, jnp.float32)),
                _g(jnp.asarray(ts, jnp.int32)), _g(rescale), *args)
        tele["lat_apply"].observe(time.perf_counter() - t0)
        tele["d_apply"].inc()
        tele["window"].set(tr._window_pos)
        step_counters["dispatches"] += 1
        step_counters["apply_dispatches"] += 1
        for p, w in zip(self._train_params, new_ws):
            p._data._data = w
        for i, s in zip(self._train_idx, new_ss):
            tr._states[i] = s
        for p, v in zip(self._frozen_params, new_frozen):
            p._data._data = v
        self._accum = new_accum if N > 1 else None
        return self._wrap_outs(outs)

    def _adopt_pending_accum(self, tr, train_vals):
        """Adopt a checkpoint-restored accumulator ring
        (``mx.checkpoint`` stages them on ``trainer._pending_accum``
        when a mid-window save is restored): the first staged ring
        whose shapes match this step's training params resumes the
        window exactly where the save left it.  A restored mid-window
        position with NO matching ring cannot resume bit-exact — that
        is a loud error, not a silent zero ring."""
        pending = getattr(tr, "_pending_accum", None)
        if pending is None:
            return None   # no checkpoint restore in this trainer's life
        if not pending:
            if tr._window_pos != 0:
                raise MXNetError(
                    "fused_step: trainer was restored mid-accumulation-"
                    f"window (micro-batch {tr._window_pos}/"
                    f"{tr._update_interval}) but its saved accumulator "
                    "ring was already adopted by another fused step — "
                    "one checkpointed ring cannot resume two windows")
            return None
        for ridx, ring in enumerate(pending):
            if len(ring) == len(train_vals) and all(
                    tuple(r.shape) == tuple(v.shape)
                    for r, v in zip(ring, train_vals)):
                return pending.pop(ridx)
        if tr._window_pos != 0:
            raise MXNetError(
                "fused_step: trainer was restored mid-accumulation-"
                f"window (micro-batch {tr._window_pos}/"
                f"{tr._update_interval}) but none of the "
                f"{len(pending)} checkpointed accumulator ring(s) "
                "match this step's parameter shapes — the checkpoint "
                "was taken with a different loss_fn/model geometry")
        return None

    def release_accounting(self):
        """Retire this step's ``train.grad_accum`` ledger entry —
        called when the trainer's FusedStep cache evicts it (its
        accumulator ring is freed with it; an un-dropped entry would
        read as a ``reconcile()`` delta<0 leak forever).  Deferred
        drop: this is also reachable from ``Trainer.__del__``, which
        may run via GC inside a thread holding the accountant lock."""
        if self._accum_key is not None:
            from ..telemetry.memory import ACCOUNTANT

            ACCOUNTANT.drop_deferred("train.grad_accum",
                                     self._accum_key)
            self._accum_key = None

    def _wrap_outs(self, outs):
        from ..ndarray.ndarray import NDArray

        nd = [NDArray(o) for o in outs]
        if self._pure is not None and self._pure.out_struct.get("is_seq"):
            return tuple(nd)
        return nd[0]

    # ------------------------------------------------------------------ #
    def _legacy(self, nd_batch, batch_size):
        """Phase-by-phase fallback: record → tape backward →
        ``Trainer.step`` — the exact pre-fusion sequence (bit-for-bit at
        ``update_interval=1``).  For N > 1, ``grad_req='write'`` params
        accumulate host-side across the window (``'add'`` params already
        accumulate in their grad buffer); ``Trainer.step`` fires the
        apply at the boundary with the effective-batch rescale."""
        from .. import autograd

        tr = self._trainer
        step_counters["legacy_steps"] += 1
        _instruments()["d_legacy"].inc()
        with autograd.record(train_mode=self._train_mode):
            out = self._loss_fn(*nd_batch)
        loss = out[0] if isinstance(out, (tuple, list)) else out
        autograd.backward([loss])
        N = tr._update_interval
        if N > 1:
            write_live = [p for p in tr._params
                          if p.grad_req == "write" and p._data is not None
                          and p._data._grad is not None]
            grads_now = [p.grad()._data for p in write_live]
            if tr._window_pos == 0 or self._legacy_accum is None:
                self._legacy_accum = grads_now
            else:
                self._legacy_accum = [a + g for a, g in
                                      zip(self._legacy_accum, grads_now)]
            if tr._window_pos + 1 >= N:
                for p, a in zip(write_live, self._legacy_accum):
                    p.grad()._rebind(a)
                self._legacy_accum = None
        tr._accum_managed = True  # this fallback accumulates 'write'
        try:                      # grads itself (above)
            tr.step(batch_size)
        finally:
            tr._accum_managed = False
        return out
