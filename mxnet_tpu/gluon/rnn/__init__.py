"""Gluon RNN package.

Reference surface: ``python/mxnet/gluon/rnn/`` (SURVEY.md §3.2 "Gluon
layers" rnn row): fused ``RNN/LSTM/GRU`` layers backed by the cuDNN RNN op
plus unrolled cells (``LSTMCell``/``GRUCell``/wrappers).

TPU-native: the "fused" layers are one ``lax.scan`` over time compiled by
XLA (the cuDNN analog — one kernel for the whole sequence), cells are pure
step functions, and both share the same math so ``unroll`` == fused.
"""
from .rnn_layer import RNN, LSTM, GRU
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, BidirectionalCell, DropoutCell,
                       ResidualCell, ZoneoutCell)

__all__ = ["RNN", "LSTM", "GRU", "RecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ResidualCell", "ZoneoutCell"]
