"""Unrolled recurrent cells.

Reference surface: ``python/mxnet/gluon/rnn/rnn_cell.py`` (SURVEY.md §3.2
"Gluon layers" rnn row): ``RNNCell``/``LSTMCell``/``GRUCell`` step
functions plus the ``SequentialRNNCell``/``BidirectionalCell``/
``DropoutCell``/``ResidualCell``/``ZoneoutCell`` wrappers and the
``unroll`` driver.

TPU-native: a cell is a pure step function; ``unroll`` is a Python loop
that traces into one XLA computation when the surrounding block is
hybridized (the reference's "hybridizable unroll").  Gate orders match the
reference fused RNN op (LSTM: i, f, g, o; GRU: r, z, n with
``n = tanh(i2h_n + r * h2h_n)``) so cell and fused-layer parameters are
interchangeable.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn  # noqa: F401  (Activation lookup)

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ResidualCell", "ZoneoutCell"]


class RecurrentCell(HybridBlock):
    """Base cell (reference anchor ``class RecurrentCell``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states: list of zeros (reference ``begin_state``)."""
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            states.append(func(shape=shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Run the cell ``length`` steps.  ``inputs`` is (N, T, C) for NTC
        (or a list of T tensors); returns (outputs, states)."""
        from ... import ndarray as F
        inputs, batch_size = _format_sequence(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            out, states = self(inputs[i], states)
            outputs.append(out)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            # take each sequence's state at its valid_length step and zero
            # the outputs past it (reference semantics)
            n_states = len(states)
            states = [
                F.SequenceLast(F.stack(*[s[j] for s in all_states], axis=0),
                               sequence_length=valid_length,
                               use_sequence_length=True, axis=0)
                for j in range(n_states)]
            outputs = _mask_sequence(outputs, valid_length)
        outputs = _merge_sequence(outputs, layout, merge_outputs)
        return outputs, states

    def forward(self, x, states):
        self._counter += 1
        return super().forward(x, states)


HybridRecurrentCell = RecurrentCell


def _format_sequence(length, inputs, layout):
    from ... import ndarray as F
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        if length is not None and len(inputs) != length:
            raise MXNetError(f"unroll: len(inputs) {len(inputs)} != "
                             f"length {length}")
        batch = inputs[0].shape[layout.find("N")]
        return list(inputs), batch
    batch = inputs.shape[layout.find("N")]
    seq = F.split(inputs, num_outputs=inputs.shape[axis], axis=axis,
                  squeeze_axis=True)
    if not isinstance(seq, list):
        seq = [seq]
    return seq, batch


def _mask_sequence(outputs, valid_length):
    from ... import ndarray as F
    masked = []
    for i, out in enumerate(outputs):
        keep = (valid_length > i).astype(out.dtype)
        masked.append(out * keep.reshape((-1,) + (1,) * (out.ndim - 1)))
    return masked


def _merge_sequence(outputs, layout, merge):
    from ... import ndarray as F
    if merge is False:
        return outputs
    axis = layout.find("T")
    return F.stack(*outputs, axis=axis)


class _BaseCell(RecurrentCell):
    """Shared parameter plumbing for RNN/LSTM/GRU cells."""

    _num_gates = 1

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ng * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self.i2h_weight.shape[0], x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _linear(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                h2h_bias):
        i2h = F.dot(x, i2h_weight, transpose_b=True) + i2h_bias
        h2h = F.dot(states[0], h2h_weight, transpose_b=True) + h2h_bias
        return i2h, h2h


class RNNCell(_BaseCell):
    """Elman cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    _num_gates = 1

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, **kwargs)
        self._activation = activation

    @property
    def _gate_names(self):
        return ("",)

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h, h2h = self._linear(F, x, states, i2h_weight, h2h_weight,
                                i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    """LSTM cell, gate order (i, f, g, o) matching the reference fused op
    (so ``LSTMBias``'s forget-gate chunk is [H:2H])."""

    _num_gates = 4

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h, h2h = self._linear(F, x, states, i2h_weight, h2h_weight,
                                i2h_bias, h2h_bias)
        g = i2h + h2h
        gi, gf, gg, go = F.split(g, num_outputs=4, axis=-1)
        c_prev = states[1]
        i = F.sigmoid(gi)
        f = F.sigmoid(gf)
        gg = F.tanh(gg)
        o = F.sigmoid(go)
        c = f * c_prev + i * gg
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(_BaseCell):
    """GRU cell, gate order (r, z, n) with the reference's
    ``n = tanh(i2h_n + r * h2h_n)``."""

    _num_gates = 3

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.dot(x, i2h_weight, transpose_b=True) + i2h_bias
        h2h = F.dot(states[0], h2h_weight, transpose_b=True) + h2h_bias
        i_r, i_z, i_n = F.split(i2h, num_outputs=3, axis=-1)
        h_r, h_z, h_n = F.split(h2h, num_outputs=3, axis=-1)
        r = F.sigmoid(i_r + h_r)
        z = F.sigmoid(i_z + h_z)
        n = F.tanh(i_n + r * h_n)
        h = (1 - z) * n + z * states[0]
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells; state list is the concatenation of child states."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)
        return self

    def state_info(self, batch_size=0):
        return sum((c.state_info(batch_size)
                    for c in self._children.values()), [])

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return sum((c.begin_state(batch_size, func, **kwargs)
                    for c in self._children.values()), [])

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, x, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, new_states = cell(x, states[pos:pos + n])
            pos += n
            next_states.extend(new_states)
        return x, next_states

    def hybrid_forward(self, F, x, states):
        return self.forward(x, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        # unroll layer-by-layer so each inner scan stays small
        if begin_state is None:
            _, batch = _format_sequence(length, inputs, layout)
            begin_state = self.begin_state(batch)
        pos = 0
        next_states = []
        cells = list(self._children.values())
        for i, cell in enumerate(cells):
            n = len(cell.state_info())
            inputs, states = cell.unroll(
                length, inputs, begin_state[pos:pos + n], layout,
                merge_outputs=None if i < len(cells) - 1 else merge_outputs,
                valid_length=valid_length)
            pos += n
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(RecurrentCell):
    """Apply dropout to the input of each step."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, x, states):
        if self._rate:
            x = F.Dropout(x, p=self._rate)
        return x, states


class _ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference
    ``ModifierCell``)."""

    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return self.base_cell.begin_state(batch_size, func, **kwargs)


class ResidualCell(_ModifierCell):
    """out = base(x) + x."""

    def hybrid_forward(self, F, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states


class ZoneoutCell(_ModifierCell):
    """Zoneout: randomly preserve previous states (reference
    ``ZoneoutCell``)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, x, states):
        from ... import autograd
        out, new_states = self.base_cell(x, states)
        if autograd.is_training():
            if self._zoneout_outputs:
                prev = self._prev_output
                if prev is None:
                    prev = F.zeros_like(out)
                mask = F.Dropout(F.ones_like(out), p=self._zoneout_outputs)
                out = F.where(mask, out, prev)
            if self._zoneout_states:
                new_states = [
                    F.where(F.Dropout(F.ones_like(ns),
                                      p=self._zoneout_states), ns, s)
                    for ns, s in zip(new_states, states)]
        self._prev_output = out.detach() if hasattr(out, "detach") else out
        return out, new_states


class BidirectionalCell(RecurrentCell):
    """Run two cells over the sequence in opposite directions; only
    meaningful through ``unroll``."""

    def __init__(self, l_cell, r_cell):
        super().__init__(prefix=None, params=None)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size) +
                self.r_cell.state_info(batch_size))

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return (self.l_cell.begin_state(batch_size, func, **kwargs) +
                self.r_cell.begin_state(batch_size, func, **kwargs))

    def __call__(self, *args, **kwargs):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        inputs, batch = _format_sequence(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch)
        nl = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, inputs, begin_state[:nl], layout="NTC"
            if layout != "TNC" else layout, merge_outputs=False,
            valid_length=valid_length)
        r_out, r_states = self.r_cell.unroll(
            length, list(reversed(inputs)), begin_state[nl:],
            layout="NTC" if layout != "TNC" else layout,
            merge_outputs=False, valid_length=valid_length)
        outs = [F.concat(lo, ro, dim=-1)
                for lo, ro in zip(l_out, reversed(r_out))]
        outs = _merge_sequence(outs, layout, merge_outputs)
        return outs, l_states + r_states
