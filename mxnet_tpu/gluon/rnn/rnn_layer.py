"""Fused recurrent layers: RNN / LSTM / GRU.

Reference surface: ``python/mxnet/gluon/rnn/rnn_layer.py`` (SURVEY.md §3.2
"Gluon layers" rnn row): layers backed by the fused ``RNN`` operator
(cuDNN LSTM/GRU + native CPU, ``src/operator/nn/rnn*``).

TPU-native: the fused op is ``ops.rnn.fused_rnn`` — one ``lax.scan`` per
(layer, direction) compiled by XLA, gate math shared with the unrolled
cells.  Parameter names follow the reference layout
``{l|r}{layer}_{i2h|h2h}_{weight|bias}`` so checkpoints interchange with
cell-based models via ``LSTM(...)[l0_i2h_weight] == LSTMCell.i2h_weight``.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0.0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout}; expected TNC or NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        ng = _GATES[mode]
        with self.name_scope():
            for layer in range(num_layers):
                for d, dname in enumerate(["l", "r"][:self._dir]):
                    in_size = input_size if layer == 0 \
                        else hidden_size * self._dir
                    for kind, shape in (
                            ("i2h_weight", (ng * hidden_size, in_size)),
                            ("h2h_weight", (ng * hidden_size, hidden_size)),
                            ("i2h_bias", (ng * hidden_size,)),
                            ("h2h_bias", (ng * hidden_size,))):
                        name = f"{dname}{layer}_{kind}"
                        init = {"i2h_weight": i2h_weight_initializer,
                                "h2h_weight": h2h_weight_initializer,
                                "i2h_bias": i2h_bias_initializer,
                                "h2h_bias": h2h_bias_initializer}[kind]
                        p = self.params.get(name, shape=shape, dtype=dtype,
                                            init=init,
                                            allow_deferred_init=True)
                        setattr(self, name, p)

    def infer_shape(self, x, *args):
        in_size = x.shape[-1]
        ng = _GATES[self._mode]
        for d in ["l", "r"][:self._dir]:
            p = getattr(self, f"{d}0_i2h_weight")
            if p.shape[-1] == 0:
                p.shape = (ng * self._hidden_size, in_size)

    def state_info(self, batch_size=0):
        n = self._num_layers * self._dir
        if self._mode == "lstm":
            return [{"shape": (n, batch_size, self._hidden_size)},
                    {"shape": (n, batch_size, self._hidden_size)}]
        return [{"shape": (n, batch_size, self._hidden_size)}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def __call__(self, x, states=None, **kwargs):
        return super().__call__(x, *([states] if states is not None else []),
                                **kwargs)

    def forward(self, x, states=None):
        from ... import autograd, ndarray as F
        from ..parameter import DeferredInitializationError
        try:
            params = {n: p.data() for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self.infer_shape(x)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            params = {n: p.data() for n, p in self._reg_params.items()}

        batch = x.shape[self._layout.find("N")]
        return_states = states is not None
        if states is None:
            states = self.begin_state(batch, dtype=x.dtype)
        if isinstance(states, F.NDArray):
            states = [states]

        arrays = [x] + list(states)
        for layer in range(self._num_layers):
            for d in ["l", "r"][:self._dir]:
                for kind in ("i2h_weight", "h2h_weight", "i2h_bias",
                             "h2h_bias"):
                    arrays.append(params[f"{d}{layer}_{kind}"])

        out = F.fused_rnn(
            arrays, mode=self._mode, num_layers=self._num_layers,
            bidirectional=self._dir == 2, dropout=self._dropout,
            training=autograd.is_training(), layout=self._layout)
        if self._mode == "lstm":
            output, h_n, c_n = out
            new_states = [h_n, c_n]
        else:
            output, h_n = out
            new_states = [h_n]
        if return_states:
            return output, new_states
        return output

    def hybrid_forward(self, F, x, *args, **params):
        return self.forward(x, *args)

    def __repr__(self):
        s = (f"{type(self).__name__}({self._hidden_size}, "
             f"num_layers={self._num_layers}, layout={self._layout}"
             f"{', bidirectional' if self._dir == 2 else ''})")
        return s


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu or tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(mode, hidden_size, num_layers, **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference fused ``RNN`` op, mode='lstm')."""

    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer GRU (reference fused ``RNN`` op, mode='gru')."""

    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("gru", hidden_size, num_layers, **kwargs)
