"""Gluon Parameter / ParameterDict.

Reference surface: ``python/mxnet/gluon/parameter.py`` (SURVEY.md §3.2
"Gluon core": Parameter with deferred shape inference at first forward,
per-ctx replicated ``data()/grad()`` copies, ``grad_req`` write/add/null,
``ParameterDict`` prefix scoping + sharing, ``Constant``).

TPU-native redesign: a Parameter owns ONE canonical NDArray (optionally with
a ``NamedSharding`` laying it out over a device mesh) instead of per-GPU
replicas — replication/sharding is a GSPMD property of the array, not N
copies.  ``list_data()/list_ctx()`` keep the reference API for porting; with
a single-device context they return singleton lists.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "ParameterDict",
           "DeferredInitializationError", "params_swapped"]


import contextlib
import threading

# Serializes TRACE-TIME work across threads: ``params_swapped`` rebinds
# the model's shared Parameter arrays to tracers while a program traces,
# so a second thread reading weights (another trace, or an engine
# collecting operand values) mid-swap would capture a leaked tracer.
# The serving loop (``mxnet_tpu.serve``) runs on its own thread next to
# user calls of ``kv_generate`` or jit-by-default ``net(x)`` forwards on
# the same model — decode engine construction, the traced decode bodies'
# swap scopes, and ``_CachedOp.__call__`` all acquire this lock.
# Compiled executions never re-run the Python body, so steady state
# never contends.
_TRACE_LOCK = threading.RLock()


@contextlib.contextmanager
def params_swapped(params, vals):
    """Temporarily rebind each Parameter's NDArray to a (traced) value,
    clearing autograd entries, and restore on exit — the trace-time swap
    discipline shared by ``_CachedOp`` tracing, ``SPMDTrainer``'s fused
    step, and ``kv_generate`` (weights ride as traced jit ARGUMENTS, so
    weight updates never invalidate compiled programs)."""
    saved = [(p._data._data, p._data._autograd_node, p._data._autograd_idx)
             for p in params]
    try:
        for p, v in zip(params, vals):
            p._data._data = v
            p._data._autograd_node = None
        yield
    finally:
        for p, (v, node, idx) in zip(params, saved):
            p._data._data = v
            p._data._autograd_node = node
            p._data._autograd_idx = idx


class DeferredInitializationError(MXNetError):
    """Raised when ``data()`` is called before shapes are known (reference
    anchor: "deferred initialization" error string)."""


def _shape_is_known(shape) -> bool:
    if shape is None:
        return False
    return all(s is not None and s > 0 for s in shape)


class Parameter:
    """A trainable tensor held by Blocks.

    ``grad_req``: 'write' (overwrite each backward), 'add' (accumulate;
    caller zero-grads), 'null' (no gradient — aux states like BN moving
    stats)."""

    def __init__(self, name, grad_req="write", shape=None, dtype=onp.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        self._data: Optional[NDArray] = None
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req
        self._stype = stype
        self._grad_stype = grad_stype
        self._data: Optional[NDArray] = None
        self._deferred_init = None  # (init, ctx, default_init)
        self._trainer = None
        self._sharding = None  # jax.sharding.NamedSharding when meshed
        # legacy multi-device DP: ctx-key -> replica NDArray when
        # initialized with a multi-ctx list (reference per-ctx ``data()``
        # copies, SURVEY.md §3.3 DP row); None for the canonical
        # single-array / GSPMD paths
        self._replicas = None

    # ------------------------------------------------------------------ #
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req}")
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        reps = getattr(self, "_replicas", None)  # setter runs in __init__
        for arr in (reps.values() if reps is not None
                    else ([self._data] if self._data is not None else [])):
            if req == "null":
                arr._grad = None
                arr._grad_req = "null"
            else:
                arr.attach_grad(req)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if new_shape is None:
            return
        new_shape = tuple(new_shape)
        if self._shape is not None:
            if len(self._shape) != len(new_shape) or any(
                    s not in (0, None) and s != n
                    for s, n in zip(self._shape, new_shape)):
                raise MXNetError(
                    f"shape mismatch for {self.name}: {self._shape} vs "
                    f"{new_shape}")
        self._shape = new_shape

    @property
    def stype(self):
        return self._stype

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={onp.dtype(self.dtype).name})")

    # ------------------------------------------------------------------ #
    # initialization
    # ------------------------------------------------------------------ #
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Create and fill the canonical array.  Deferred when the shape has
        unknown (0) dims (reference deferred-init mechanism)."""
        default_init = init_mod.create(default_init) if default_init is not None \
            else init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if not _shape_is_known(self._shape):
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"cannot initialize {self.name}: shape {self._shape} "
                    "unknown and allow_deferred_init=False")
            self._deferred_init = (init, ctx, default_init)
            return
        self._init_impl(init, ctx, default_init)

    @staticmethod
    def _ctx_key(ctx):
        return (ctx.device_type, ctx.device_id)

    def _init_impl(self, init, ctx_list, default_init):
        # Explicit init (param-level ``self.init`` or the ``init`` argument)
        # rides the InitDesc ``__init__`` attr so the global initializer's
        # name-suffix dispatch is bypassed (reference Parameter._init_impl).
        explicit = init_mod.create(init) if init is not None \
            else init_mod.create(self.init)
        ctx = ctx_list[0]
        arr = NDArray(jnp.zeros(self._shape, jnp.dtype(self.dtype)), ctx)
        desc = init_mod.InitDesc(
            self.name, {"__init__": explicit} if explicit is not None else {})
        default_init(desc, arr)
        if self._sharding is not None:
            arr._rebind(jax.device_put(arr._data, self._sharding))
        elif ctx is not None:
            arr._rebind(jax.device_put(arr._data, ctx.jax_device()))
        self._set_data_arr(arr)
        if len(ctx_list) > 1 and self._sharding is None:
            # reference per-ctx replicas: same values device_put to every
            # ctx, each replica with its OWN grad buffer
            self._replicas = OrderedDict()
            self._replicas[self._ctx_key(ctx)] = arr
            for c in ctx_list[1:]:
                rep = NDArray(jax.device_put(arr._data, c.jax_device()), c)
                if self._grad_req != "null":
                    rep.attach_grad(self._grad_req)
                self._replicas[self._ctx_key(c)] = rep
        self._deferred_init = None

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not _shape_is_known(self._shape):
            raise DeferredInitializationError(
                f"parameter {self.name} has unknown shape {self._shape}; "
                "run a forward pass to infer it or set the shape explicitly")
        init, ctx, default_init = self._deferred_init
        self._init_impl(init, ctx, default_init)

    def _set_data_arr(self, arr: NDArray):
        self._data = arr
        if self._grad_req != "null":
            arr.attach_grad(self._grad_req)

    # ------------------------------------------------------------------ #
    # access
    # ------------------------------------------------------------------ #
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"parameter {self.name} deferred; forward once to infer "
                "shapes")
        raise MXNetError(
            f"parameter {self.name} not initialized; call "
            ".initialize() first")

    def data(self, ctx=None) -> NDArray:
        self._check_initialized()
        if self._replicas is not None and ctx is not None:
            key = self._ctx_key(ctx)
            if key not in self._replicas:
                raise MXNetError(
                    f"parameter {self.name} was not initialized on "
                    f"context {ctx} (replicas on "
                    f"{list(self._replicas)})")
            return self._replicas[key]
        return self._data

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized()
        arr = self.data(ctx)
        if self._grad_req == "null" or arr._grad is None:
            raise MXNetError(
                f"cannot get grad for {self.name}: grad_req is 'null'")
        return arr._grad

    def list_data(self):
        self._check_initialized()
        if self._replicas is not None:
            return list(self._replicas.values())
        return [self.data()]

    def list_grad(self):
        if self._replicas is not None:
            return [r._grad for r in self._replicas.values()]
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        if self._replicas is not None:
            return [r.context for r in self._replicas.values()]
        return [self._data.context]

    def _sync_replicas(self):
        """Broadcast the primary replica's value to the others (after an
        optimizer update — the reference's kvstore weight pull)."""
        if self._replicas is None:
            return
        src = self._data._data
        for key, rep in self._replicas.items():
            if rep is self._data:
                continue
            rep._rebind(jax.device_put(src, rep.context.jax_device()))

    def set_data(self, data):
        """Replace the value, preserving the grad buffer (reference
        ``Parameter.set_data``)."""
        self.shape = tuple(data.shape)
        if self._data is None:
            if self._deferred_init is not None:
                init, ctx, default_init = self._deferred_init
                self._deferred_init = None
                arr = data if isinstance(data, NDArray) else NDArray(
                    jnp.asarray(data, jnp.dtype(self.dtype)))
                self._set_data_arr(
                    NDArray(jnp.asarray(arr._data, jnp.dtype(self.dtype)),
                            ctx[0] if ctx else None))
                return
            raise MXNetError(f"parameter {self.name} not initialized")
        src = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        if self._sharding is not None:
            src = jax.device_put(src, self._sharding)
        self._data._rebind(jnp.asarray(src, self._data._data.dtype))
        self._sync_replicas()

    def _load_init(self, src, ctx=None):
        """Set the value from a loaded array (``load_parameters`` /
        ``ParameterDict.load``): cast to ``self.dtype``, honor the requested
        ctx (falling back to the ctx captured by a pending deferred init),
        apply sharding, and never pay a random init that would be
        overwritten."""
        self.shape = tuple(src.shape)
        data = jnp.asarray(src._data if isinstance(src, NDArray) else src,
                           jnp.dtype(self.dtype))
        c = None
        if ctx is not None:
            c = ctx[0] if isinstance(ctx, (list, tuple)) else ctx
        elif self._deferred_init is not None:
            dctx = self._deferred_init[1]
            if dctx is not None:
                c = dctx[0] if isinstance(dctx, (list, tuple)) else dctx
        self._deferred_init = None
        if self._sharding is not None:
            from ..parallel.mesh import global_put

            data = global_put(data, self._sharding)
        elif isinstance(c, Context):
            data = jax.device_put(data, c.jax_device())
        if self._data is None:
            self._set_data_arr(NDArray(data, c))
        else:
            self._data._rebind(jnp.asarray(data, self._data._data.dtype))
            self._sync_replicas()

    def zero_grad(self):
        if self._replicas is not None:
            for r in self._replicas.values():
                if r._grad is not None:
                    r.zero_grad()
            return
        if self._data is not None and self._data._grad is not None:
            self._data.zero_grad()

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data._rebind(
                jax.device_put(self._data._data, ctx.jax_device())
                if isinstance(ctx, Context) else self._data._data)

    def cast(self, dtype):
        self.dtype = dtype
        arrs = list(self._replicas.values()) if self._replicas is not None \
            else ([self._data] if self._data is not None else [])
        for arr in arrs:
            had_grad = arr._grad is not None
            arr._rebind(arr._data.astype(jnp.dtype(dtype)))
            if had_grad:
                arr.attach_grad(self._grad_req)

    # -- sharding (TPU-native extension) -------------------------------- #
    def set_sharding(self, sharding):
        """Attach a ``jax.sharding.NamedSharding`` — the GSPMD analog of the
        reference's per-device replica lists (SURVEY.md §3.3 TP row)."""
        self._sharding = sharding
        if self._data is not None and sharding is not None:
            from ..parallel.mesh import global_put

            self._data._rebind(global_put(self._data._data, sharding))

    # -- symbol-compat ---------------------------------------------------- #
    def var(self):
        return self.data()


class Constant(Parameter):
    """Non-trainable parameter with a fixed value (reference anchor
    ``Constant``)."""

    def __init__(self, name, value):
        if isinstance(value, NDArray):
            arr = value.asnumpy()
        else:
            arr = onp.asarray(value, onp.float32)
        self.value = arr
        super().__init__(name, grad_req="null", shape=arr.shape,
                         dtype=arr.dtype,
                         init=init_mod.Constant(arr))


class ParameterDict:
    """Ordered name->Parameter mapping with prefix scoping and sharing
    (reference anchor ``ParameterDict``)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    def __repr__(self):
        lines = "\n".join(f"  {p!r}" for p in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{lines}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs) -> Parameter:
        """Get-or-create ``prefix+name`` (shared dict consulted first)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for k, v in kwargs.items():
                if k == "shape":
                    param.shape = v
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
        return param

    def get_constant(self, name, value=None) -> Constant:
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant {full} and no value given")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full):
        if full in self._params:
            return self._params[full]
        if self._shared is not None and full in self._shared:
            self._params[full] = self._shared[full]
            return self._params[full]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter {k}")
            self._params[k] = v

    # -- bulk ops --------------------------------------------------------- #
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        default = init_mod.create(init) if init is not None else \
            init_mod.Uniform()
        for p in self._params.values():
            p.initialize(None, ctx, default, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import serialization
        arrays = {}
        for name, p in self._params.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arrays[name] = p.data()
        serialization.save(filename, arrays)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import serialization
        loaded = serialization.load(filename)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p._load_init(loaded[name], ctx)
            elif not allow_missing:
                raise MXNetError(f"missing parameter {name} in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)}")
