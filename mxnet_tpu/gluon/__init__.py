"""Gluon — the high-level training API (reference:
``python/mxnet/gluon/``, SURVEY.md §3.2 / L9)."""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss

from importlib import import_module as _imp


def __getattr__(name):
    _lazy = {
        "rnn": ".rnn",
        "data": ".data",
        "model_zoo": ".model_zoo",
        "contrib": ".contrib",
        "utils": ".utils",
    }
    if name == "Trainer":
        from .trainer import Trainer
        return Trainer
    if name in _lazy:
        mod = _imp(_lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
