"""Gluon Block / HybridBlock — define-by-run modules with trace-to-XLA
hybridization.

Reference surface: ``python/mxnet/gluon/block.py`` (SURVEY.md §3.2 "Gluon
core"; §4.2 call stack): ``Block`` (child registry, collect_params,
save/load_parameters, hooks), ``HybridBlock.hybridize()`` builds a
``CachedOp`` — the reference's hybridization engine
(``src/imperative/cached_op.cc``) that traces ``hybrid_forward`` once per
input signature and replays the cached graph.

TPU-native redesign (SURVEY.md §7 "Hybridize/CachedOp"): hybridize traces the
block's imperative forward into ONE pure jax function
``fn(key, *params, *inputs) -> (*outputs, *aux_updates)`` and wraps it in
``jax.jit`` — jit's shape/dtype-keyed trace cache plays the role of the
reference's per-(shape,dtype,ctx) ``GraphInfo`` cache, and XLA fusion plays
op bulking.  When autograd is recording, the jitted function is invoked
through the op registry so the tape records ONE CachedOp node (exactly like
the reference records one CachedOp node, §4.2).  Mutable state (BatchNorm
moving stats) is returned functionally as aux outputs and committed after
execution — no tracer ever leaks into a Parameter.

Jit-by-default: a NON-hybridized HybridBlock called at inference time
(positional NDArray inputs, no autograd recording, no enclosing trace)
routes through the same CachedOp trace cache automatically, so zoo models
drop into predict loops and the decode server without a manual
``hybridize()``.  A block whose forward is not trace-safe falls back to
imperative execution permanently (first failed trace); explicit
``hybridize(False)`` opts out; ``MXNET_JIT_BY_DEFAULT=0`` restores
always-imperative.
"""
from __future__ import annotations

import contextlib
import os
import re
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "_TraceState",
           "trace_scope"]


# --------------------------------------------------------------------------- #
# naming scope (reference anchor ``_BlockScope`` in gluon/block.py)
# --------------------------------------------------------------------------- #

class _BlockScope:
    _current = threading.local()
    _global_counter: dict = {}

    def __init__(self, block):
        self._block = block
        self._counter: dict = {}
        self._old = None

    @staticmethod
    def create(prefix, params, hint):
        """Return (prefix, ParameterDict) for a new Block."""
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                count = _BlockScope._global_counter.get(hint, 0)
                _BlockScope._global_counter[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        full_prefix = current._block.prefix + prefix
        if params is None:
            params = ParameterDict(full_prefix)
        else:
            params = ParameterDict(params.prefix, params)
        return full_prefix, params

    def __enter__(self):
        self._old = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        _BlockScope._current.value = self._old


# --------------------------------------------------------------------------- #
# trace state: set while a CachedOp traces/executes; layers consult it to
# stage aux-state updates instead of mutating Parameters (tracer-leak guard)
# --------------------------------------------------------------------------- #

class _TraceState(threading.local):
    def __init__(self):
        self.stack = []  # list of OrderedDict{id(param): (param, value)}
        self.no_hybrid = 0  # >0: force imperative forward (inline children)

    @property
    def active(self):
        return bool(self.stack)

    def stage(self, param, value):
        self.stack[-1][id(param)] = (param, value)


_trace_state = _TraceState()


@contextlib.contextmanager
def trace_scope(key, training):
    """The CachedOp trace discipline as a reusable scope, shared by
    ``_CachedOp`` tracing, ``SPMDTrainer``'s fused SPMD step and the
    fused train step (``gluon/fused_step.py``): aux-state updates (BN
    moving stats) are STAGED functionally instead of mutating Parameters,
    the RNG ``key`` is threaded to random ops (``mxrandom.next_key``
    splits it instead of the eager global key), autograd is paused in
    ``training`` mode, and nested CachedOps are inlined (``_no_hybrid``).
    Yields the aux OrderedDict ``id(param) -> (param, staged_value)``."""
    from .. import autograd, random as mxrandom

    aux: OrderedDict = OrderedDict()
    _trace_state.stack.append(aux)
    mxrandom.push_trace_key(key)
    try:
        with autograd.pause(train_mode=training), _no_hybrid():
            yield aux
    finally:
        mxrandom.pop_trace_key()
        _trace_state.stack.pop()


def commit_aux(param: Parameter, value):
    """Commit an aux-state update (e.g. BN moving stats).  Inside a trace:
    staged as a functional output; imperatively: set_data under pause."""
    from .. import autograd

    data = value._data if isinstance(value, NDArray) else value
    if _trace_state.active:
        _trace_state.stage(param, data)
    else:
        with autograd.pause():
            param.set_data(NDArray(data))


# --------------------------------------------------------------------------- #
# Block
# --------------------------------------------------------------------------- #

class Block:
    """Base container (reference anchor ``class Block``)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        hint = _classname_hint(type(self).__name__)
        self._prefix, self._params = _BlockScope.create(prefix, params, hint)
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._forward_hooks: "OrderedDict[int, callable]" = OrderedDict()
        self._forward_pre_hooks: "OrderedDict[int, callable]" = OrderedDict()

    # -- naming ----------------------------------------------------------- #
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """``with self.name_scope():`` — children/params created inside get
        hierarchical names."""
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    # -- registration ----------------------------------------------------- #
    def __setattr__(self, name, value):
        # deregister on overwrite so a replaced child/param doesn't linger in
        # collect_params()/save_parameters() (reference raises TypeError on
        # type-changing reassignment; we allow it but keep registries exact)
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg.pop(name, None)
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing.pop(name, None)
        else:
            for regname in ("_children", "_reg_params"):
                reg = self.__dict__.get(regname)
                if reg is not None:
                    reg.pop(name, None)
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block
        return block

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks, hook)
        return handle

    def register_forward_pre_hook(self, hook):
        return _HookHandle(self._forward_pre_hooks, hook)

    # -- parameter management --------------------------------------------- #
    def collect_params(self, select=None) -> ParameterDict:
        """All params in the subtree, optionally regex-filtered (reference
        ``collect_params('.*weight')``)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self._params.items()
                        if pat.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        """Structure-based names ('features.0.weight') used by
        save_parameters (reference ``_collect_params_with_prefix``)."""
        if prefix:
            prefix += "."
        ret = {prefix + n: p for n, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)
        return self

    def save_parameters(self, filename, deduplicate=False):
        """Reference format: dict of structured-name -> array (``.params``
        binary, ndarray/serialization.py)."""
        from ..ndarray import serialization
        params = self._collect_params_with_prefix()
        arrays = {}
        seen = {}
        for name, p in params.items():
            if deduplicate and id(p) in seen:
                continue
            seen[id(p)] = name
            arrays[name] = p.data()
        serialization.save(filename, arrays)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import serialization
        loaded = serialization.load(filename)
        params = self._collect_params_with_prefix()
        if not any("." in k for k in loaded) and any("." in k for k in params):
            # file uses flat parameter names (ParameterDict.save) — remap
            byname = {p.name: p for p in params.values()}
            for k, v in loaded.items():
                if k in byname:
                    _load_one(byname[k], v, ctx)
                elif not ignore_extra:
                    raise MXNetError(f"extra parameter {k} in {filename}")
            if not allow_missing:
                missing = set(byname) - set(loaded)
                if missing:
                    raise MXNetError(
                        f"missing parameters in {filename}: {sorted(missing)}")
            return
        for name, p in params.items():
            if name in loaded:
                _load_one(p, loaded[name], ctx)
            elif not allow_missing:
                raise MXNetError(f"missing parameter {name} in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)}")

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self.params.values():
            p.cast(dtype)
        return self

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    def hybridize(self, active=True, **kwargs):
        """Recursive; plain Blocks only forward to children (reference
        behavior)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-layer summary table (reference ``Block.summary``)."""
        rows = []

        def walk(block, depth):
            n_params = sum(int(onp.prod(p.shape)) for p in
                           block._reg_params.values()
                           if p.shape and all(s > 0 for s in p.shape))
            rows.append(("  " * depth + type(block).__name__,
                         block.name, n_params))
            for c in block._children.values():
                walk(c, depth + 1)

        walk(self, 0)
        total = sum(r[2] for r in rows)
        lines = [f"{'Layer':<40}{'Name':<30}{'Params':>12}", "-" * 82]
        lines += [f"{r[0]:<40}{r[1]:<30}{r[2]:>12}" for r in rows]
        lines.append("-" * 82)
        lines.append(f"{'Total params:':<70}{total:>12}")
        print("\n".join(lines))

    # -- forward ----------------------------------------------------------- #
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __repr__(self):
        lines = []
        for name, child in self._children.items():
            mod = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {mod}")
        body = "\n".join(lines)
        return f"{type(self).__name__}(\n{body}\n)" if body else \
            f"{type(self).__name__}()"


def _load_one(p: Parameter, src: NDArray, ctx):
    p._load_init(src, ctx)


def _classname_hint(name):
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and not name[i - 1].isupper():
            out.append("_")
        out.append(ch.lower())
    return "".join(out).replace("_", "")


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks, hook):
        self._hooks = hooks
        self._id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        hooks[self._id] = hook

    def detach(self):
        self._hooks.pop(self._id, None)


# --------------------------------------------------------------------------- #
# HybridBlock + CachedOp
# --------------------------------------------------------------------------- #

class HybridBlock(Block):
    """Block whose forward is expressed as ``hybrid_forward(F, x, *args,
    **params)`` and can be traced to one compiled XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._flags = {}
        self._last_input_structs = None
        # jit-by-default trace cache state: None = untried, True = the
        # block traces cleanly, False = opted out (explicit
        # hybridize(False) or a failed trace — stays imperative)
        self._auto_jit = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._cached_op = None
        # hybridize(False) is an explicit request for imperative
        # execution — the jit-by-default path honors it
        self._auto_jit = None if active else False
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from concrete inputs.  Builtin
        layers override this; custom blocks with deferred params must too
        (the reference solves this with symbolic shape inference; here
        inference is layer-local because execution is define-by-run)."""
        raise MXNetError(
            f"{type(self).__name__} has deferred-init parameters but no "
            "infer_shape; give explicit in_units/in_channels or override "
            "infer_shape")

    def cast(self, dtype):
        self._cached_op = None
        return super().cast(dtype)

    # -- forward dispatch -------------------------------------------------- #
    def __call__(self, *args, **kwargs):
        if args and not _trace_state.active and \
                all(isinstance(a, NDArray) for a in args):
            # raw jax dtypes — no onp.dtype/str conversion on the hot path
            self._last_input_structs = [(a._data.shape, a._data.dtype)
                                        for a in args]
        if self._active and not _trace_state.no_hybrid:
            for hook in self._forward_pre_hooks.values():
                hook(self, args)
            out = self._call_cached_op(*args, **kwargs)
            for hook in self._forward_hooks.values():
                hook(self, args, out)
            return out
        if self._auto_jit is not False and not self._active and \
                self._should_auto_jit(args, kwargs):
            # hooks run OUTSIDE the try: a hook error is a user error
            # and must propagate, not masquerade as a trace failure
            for hook in self._forward_pre_hooks.values():
                hook(self, args)
            try:
                out = self._call_cached_op(*args)
            except Exception:
                if self._auto_jit:      # worked before — real failure
                    raise
                # re-run imperatively (pre-hooks fire a second time on
                # this one fallback call).  If the re-run ALSO raises,
                # the error is real (bad input, user bug): it propagates
                # with the trace still untried so a corrected call
                # retries the jit.  Only a CLEAN imperative re-run
                # proves the forward itself is trace-hostile (value-
                # dependent Python control flow, host materialization)
                # and permanently drops the block back to imperative
                # execution.
                self._auto_jit = None
                self._cached_op = None
                out = super().__call__(*args, **kwargs)
                self._auto_jit = False
                return out
            else:
                self._auto_jit = True
                for hook in self._forward_hooks.values():
                    hook(self, args, out)
                return out
        return super().__call__(*args, **kwargs)

    def _should_auto_jit(self, args, kwargs):
        """Jit-by-default gate for non-hybridized INFERENCE calls: the
        top-level forward of a zoo model dropped into a predict loop (or
        the decode server) gets the CachedOp trace cache without a
        manual ``hybridize()``.  Engages only outside autograd
        recording and outside any active trace, for positional NDArray
        inputs (the CachedOp calling convention) — the training path
        and nested calls keep exact imperative semantics.
        ``MXNET_JIT_BY_DEFAULT=0`` restores always-imperative."""
        from .. import autograd
        if kwargs or not args or _trace_state.no_hybrid or \
                _trace_state.active or autograd.is_recording():
            return False
        if not all(isinstance(a, NDArray) for a in args):
            return False
        return os.environ.get("MXNET_JIT_BY_DEFAULT", "1") != "0"

    def forward(self, x, *args, **kwargs):
        from .. import ndarray as F
        # pick the replica matching the input's device so the legacy
        # per-ctx DP loop (split_and_load + per-ctx forward) runs each
        # slice on its own device (reference per-ctx param copies)
        ctx = x.context if isinstance(x, NDArray) and any(
            p._replicas is not None
            for p in self._reg_params.values()) else None
        try:
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            # deferred init may have just CREATED the replicas — recompute
            # the selection ctx so the first forward uses the right one
            ctx = x.context if isinstance(x, NDArray) and any(
                p._replicas is not None
                for p in self._reg_params.values()) else None
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        return self.hybrid_forward(F, x, *args, **params, **kwargs)

    def _deferred_infer_shape(self, *args):
        self.infer_shape(*args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- cached-op path ---------------------------------------------------- #
    def _call_cached_op(self, *args, **kwargs):
        if self._cached_op is None:
            self._cached_op = _CachedOp(self, self._flags)
        return self._cached_op(args, kwargs)

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Save ``path-symbol.json`` + ``path-%04d.params`` (reference
        ``HybridBlock.export``, SURVEY.md §5.4b).

        The graph is obtained by CAPTURE: one predict-mode imperative
        forward is replayed with every registry invoke recorded as a graph
        node (the reference's tape-as-graph mechanism).  Requires at least
        one prior forward call (to know input signatures) — same
        precondition as the reference."""
        from .. import autograd, ndarray as nd
        from ..symbol.symbol import capture
        if getattr(self, "_last_input_structs", None) is None:
            raise MXNetError(
                "export: run the block on real inputs once before export "
                "(the reference has the same requirement)")
        params = self.collect_params()
        inputs = [nd.zeros(tuple(s), dtype=str(onp.dtype(dt)))
                  for s, dt in self._last_input_structs]
        in_names = ["data"] if len(inputs) == 1 else \
            [f"data{i}" for i in range(len(inputs))]
        with capture() as cap:
            for name, p in params.items():
                if p._data is not None:
                    cap.mark_variable(name, p.data())
            for nm, x in zip(in_names, inputs):
                cap.mark_variable(nm, x, shape=x.shape)
            with autograd.pause(train_mode=False):
                with _no_hybrid():
                    out = self.forward(*inputs)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        sym = cap.symbol_for(outs)
        sym.save(f"{path}-symbol.json", remove_amp_cast=remove_amp_cast)
        used = set(sym.list_arguments())
        save_dict = {f"arg:{n}": p.data() for n, p in params.items()
                     if n in used and p._data is not None}
        for cname, cval in cap.const_values.items():
            if cname in used:
                save_dict[f"aux:{cname}"] = NDArray(cval)
        from ..ndarray import serialization
        serialization.save(f"{path}-{epoch:04d}.params", save_dict)
        return sym

    def optimize_for(self, x, *args, backend=None, **kwargs):
        """Reference ``optimize_for(backend)``: partition/compile for a
        backend.  XLA is the only backend; equivalent to hybridize + warmup
        call."""
        self.hybridize()
        return self(x, *args)


class _CachedOp:
    """Traced, jitted executable for one HybridBlock (the reference's
    ``CachedOp``, src/imperative/cached_op.cc).

    Pure function layout::

        fn(key, *param_arrays, *input_arrays; training) ->
            (*outputs, *aux_updates)

    jax.jit caches per shape/dtype signature (== reference GraphInfo cache);
    ``training`` is a static argument (two traces, train/eval, like the
    reference's separate fwd graphs).  When autograd records, the jitted fn
    goes through ``ops.registry.invoke`` so the tape holds ONE node whose vjp
    is the compiled backward (== "record ONE CachedOp node", SURVEY.md §4.2).
    """

    def __init__(self, block: HybridBlock, flags):
        self._block = block
        self._flags = flags
        self._param_list = None   # ordered [(name, Parameter)]
        # per-training-mode output structure, set at that mode's first trace:
        # training -> (out_count, out_is_seq, [aux Parameters])
        self._structure = {}
        self._jitted = {}         # training flag -> jitted fn

    def _ensure_params(self, args, kwargs):
        if self._param_list is not None:
            return
        # materialize deferred params with one imperative forward — via
        # forward(), not __call__(): this warmup is internal, so the
        # block's own hooks must not fire for it (the caller fires them
        # exactly once around the real execution)
        params = self._block.collect_params()
        needs_init = any(p._data is None for p in params.values())
        if needs_init:
            with _no_hybrid():
                self._block.forward(*args, **kwargs)
            params = self._block.collect_params()
        self._param_list = [(n, p) for n, p in params.items()
                            if p._data is not None]

    def _make_fn(self, training):
        block = self._block
        names = [n for n, _ in self._param_list]
        param_objs = [p for _, p in self._param_list]

        def fn(key, *arrays):
            from .parameter import params_swapped
            n = len(param_objs)
            param_vals, inputs = arrays[:n], arrays[n:]
            with trace_scope(key, training) as aux:
                with params_swapped(param_objs, param_vals):
                    nd_inputs = [NDArray(x) if not isinstance(x, NDArray)
                                 else x for x in inputs]
                    out = block.forward(*nd_inputs)

            is_seq = isinstance(out, (tuple, list))
            outs = list(out) if is_seq else [out]
            out_arrays = [o._data if isinstance(o, NDArray) else o
                          for o in outs]
            aux_params = [p for (p, _v) in aux.values()]
            aux_values = [jax.lax.stop_gradient(v) for (_p, v) in aux.values()]
            # record structure at this mode's first trace
            if training not in self._structure:
                self._structure[training] = (len(out_arrays), is_seq,
                                             aux_params)
            return tuple(out_arrays) + tuple(aux_values)

        return fn

    def _get_jitted(self, training):
        if training not in self._jitted:
            from .. import telemetry
            raw = self._make_fn(training)
            self._jitted[training] = telemetry.instrument_jit(
                jax.jit(raw), "gluon.cached_op",
                key=(self._block.name, "train" if training else "eval"),
                fields={"block": self._block.name,
                        "training": bool(training)})
        return self._jitted[training]

    def __call__(self, args, kwargs):
        from .. import autograd, random as mxrandom
        from ..ops.registry import Op, invoke
        from .parameter import _TRACE_LOCK

        if kwargs:
            raise MXNetError(
                "hybridized blocks accept positional arguments only "
                "(reference CachedOp semantics); pass extra tensors "
                "positionally or un-hybridize")
        # under _TRACE_LOCK: a first call traces with the model's shared
        # Parameters swapped to tracers, and every call reads p._data —
        # either racing a concurrent trace (e.g. the serving thread
        # retracing the same model) would capture a leaked tracer
        with _TRACE_LOCK:
            self._ensure_params(args, kwargs)
            training = autograd.is_training()
            fn = self._get_jitted(training)
            if training not in self._structure:
                # prime structure info with an eval_shape trace (no
                # device work)
                key0 = jax.random.PRNGKey(0)
                param_vals = [p._data._data for _, p in self._param_list]
                in_vals = [a._data if isinstance(a, NDArray)
                           else jnp.asarray(a) for a in args]
                jax.eval_shape(fn, key0, *param_vals, *in_vals)

            key = mxrandom.next_key()
            input_nds = [a if isinstance(a, NDArray)
                         else NDArray(jnp.asarray(a)) for a in args]
            # legacy multi-ctx DP: feed the replicas matching the input
            # device (jax.jit re-specializes per placement, like the
            # reference's per-ctx GraphInfo cache)
            in_ctx = input_nds[0].context if input_nds and any(
                p._replicas is not None for _, p in self._param_list) \
                else None
            param_nds = [p.data(in_ctx) if p._replicas is not None
                         else p._data for _, p in self._param_list]
            opref = Op(name=f"CachedOp_{self._block.name}", fn=fn)
            result = invoke(opref,
                            [NDArray(key)] + param_nds + input_nds, {})
        outs = result if isinstance(result, list) else [result]
        n_out, out_is_seq, aux_params = self._structure[training]
        primary, aux_vals = outs[:n_out], outs[n_out:]
        # commit aux updates (concrete arrays — safe)
        for p, v in zip(aux_params, aux_vals):
            with autograd.pause():
                p.set_data(v)
        if out_is_seq:
            return list(primary)
        return primary[0]


class _no_hybrid:
    """Temporarily force imperative forward for all HybridBlocks on this
    thread (used while tracing so nested CachedOps inline, like the
    reference inlines child graphs into the parent CachedOp)."""

    def __enter__(self):
        _trace_state.no_hybrid += 1
        return self

    def __exit__(self, *a):
        _trace_state.no_hybrid -= 1


class SymbolBlock(HybridBlock):
    """Wrap a Symbol graph as a Block (reference anchor
    ``SymbolBlock.imports``; SURVEY.md §5.4b "reloadable cross-language").

    Forward executes the graph through the shared op registry, so a
    SymbolBlock trains, hybridizes and exports like any other block."""

    def __init__(self, outputs, inputs, params=None, prefix=None):
        super().__init__(prefix=prefix or "symbolblock_")
        from ..symbol.symbol import Symbol
        if isinstance(outputs, (list, tuple)):
            from ..symbol.symbol import Group
            outputs = Group(outputs)
        if not isinstance(outputs, Symbol):
            raise MXNetError("SymbolBlock: outputs must be Symbol(s)")
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._sym = outputs
        self._input_names = [s.name if isinstance(s, Symbol) else str(s)
                             for s in inputs]
        self._consts = {}
        arg_names = outputs.list_arguments()
        for nm in self._input_names:
            if nm not in arg_names:
                raise MXNetError(f"SymbolBlock: input {nm} not in graph")
        for nm in arg_names:
            if nm in self._input_names:
                continue
            p = (params or {}).get(nm)
            if isinstance(p, Parameter):
                self._params._params[nm] = p
            else:
                newp = Parameter(nm, shape=None, allow_deferred_init=True)
                if p is not None:
                    newp._load_init(p)
                self._params._params[nm] = newp

    def forward(self, *args):
        feed = {}
        for nm, a in zip(self._input_names, args):
            feed[nm] = a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
        for nm, p in self._params.items():
            if nm in self._consts:
                feed[nm] = self._consts[nm]
            elif p._data is not None:
                feed[nm] = p.data()
            else:
                raise MXNetError(f"SymbolBlock: parameter {nm} has no value; "
                                 f"load params first")
        from ..symbol.symbol import _execute
        outs = _execute(self._sym._heads, feed)
        return outs[0] if len(outs) == 1 else outs

    def hybrid_forward(self, F, *args, **kwargs):
        return self.forward(*args)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported (symbol.json, .params) pair as a Block."""
        from .. import symbol as sym_mod
        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        block = SymbolBlock(sym, input_names)
        if param_file is not None:
            arg_params, aux_params = load_params_file(param_file)
            for nm, v in arg_params.items():
                if nm in block._params._params:
                    block._params._params[nm]._load_init(v)
            for nm, v in aux_params.items():
                if nm in block._params._params:
                    block._consts[nm] = v
                    block._params._params[nm]._load_init(v)
        return block


def load_params_file(param_file):
    """Split a ``.params`` file into (arg, aux) dicts — delegates to the
    single implementation in :mod:`mxnet_tpu.model`."""
    from ..model import load_params_file as _impl
    return _impl(param_file)
