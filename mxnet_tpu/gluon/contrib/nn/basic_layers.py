"""Contrib layers (reference ``gluon/contrib/nn/basic_layers.py``):
Concurrent/HybridConcurrent, Identity, SparseEmbedding, PixelShuffle{1,2,3}D.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential, Embedding

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs on ``axis``."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class HybridConcurrent(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)

    def hybrid_forward(self, F, x):
        return self.forward(x)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Reference: embedding with ``sparse_grad=True`` (row_sparse gradient
    pulled row-wise from the PS).  XLA is dense-only (SURVEY.md §3.3 sparse
    row): gradients here are dense; the API is kept so reference code runs,
    and large tables should instead be GSPMD-sharded over the mesh."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer, **kwargs)


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor,) * ndim
        self._factor = tuple(int(f) for f in factor)
        self._ndim = ndim

    def __repr__(self):
        return f"{type(self).__name__}(factor={self._factor})"


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) → (N, C, W*f) (reference ``PixelShuffle1D``)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        f, = self._factor
        n, cf, w = x.shape
        x = x.reshape((n, cf // f, f, w))
        x = F.transpose(x, axes=(0, 1, 3, 2))
        return x.reshape((n, cf // f, w * f))


class PixelShuffle2D(_PixelShuffle):
    """(N, C*f1*f2, H, W) → (N, C, H*f1, W*f2)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factor
        n, c, h, w = x.shape
        co = c // (f1 * f2)
        x = x.reshape((n, co, f1, f2, h, w))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        return x.reshape((n, co, h * f1, w * f2))


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) → (N, C, D*f1, H*f2, W*f3)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factor
        n, c, d, h, w = x.shape
        co = c // (f1 * f2 * f3)
        x = x.reshape((n, co, f1, f2, f3, d, h, w))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return x.reshape((n, co, d * f1, h * f2, w * f3))
