"""Contrib layers (reference ``gluon/contrib/nn/basic_layers.py``)."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, PixelShuffle1D, PixelShuffle2D,
                           PixelShuffle3D)
from ...nn import SyncBatchNorm  # reference exposes it under contrib.nn
