"""Gluon contrib (reference ``python/mxnet/gluon/contrib/``; SURVEY.md §3.2
"Gluon contrib" row)."""
from . import nn
from . import rnn
from . import estimator
