"""Contrib RNN cell wrappers (reference
``gluon/contrib/rnn/rnn_cell.py``): VariationalDropoutCell — the same
dropout mask reused at every timestep (Gal & Ghahramani)."""
from __future__ import annotations

from ...rnn.rnn_cell import _ModifierCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(_ModifierCell):
    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        self._mask_inputs = None
        self._mask_states = None
        self._mask_outputs = None

    def reset(self):
        super().reset()
        self._mask_inputs = None
        self._mask_states = None
        self._mask_outputs = None

    def _mask(self, F, existing, rate, like):
        from .... import autograd
        if rate == 0.0 or not autograd.is_training():
            return existing, like
        if existing is None:
            keep = 1.0 - rate
            existing = F.Dropout(F.ones_like(like), p=rate, mode="always")
        return existing, like * existing

    def hybrid_forward(self, F, x, states):
        self._mask_inputs, x = self._mask(F, self._mask_inputs,
                                          self._drop_inputs, x)
        if self._drop_states:
            self._mask_states, s0 = self._mask(F, self._mask_states,
                                               self._drop_states, states[0])
            states = [s0] + list(states[1:])
        out, next_states = self.base_cell(x, states)
        self._mask_outputs, out = self._mask(F, self._mask_outputs,
                                             self._drop_outputs, out)
        return out, next_states

    def __repr__(self):
        return (f"VariationalDropoutCell(in={self._drop_inputs}, "
                f"state={self._drop_states}, out={self._drop_outputs})")
