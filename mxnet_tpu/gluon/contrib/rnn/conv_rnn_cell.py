"""Convolutional RNN/LSTM/GRU cells (reference
``gluon/contrib/rnn/conv_rnn_cell.py``): recurrence with conv i2h/h2h —
spatial state for video/spatiotemporal models.
"""
from __future__ import annotations

import numpy as onp

from ....base import MXNetError
from ...rnn.rnn_cell import RecurrentCell
from ...parameter import Parameter

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuple(x, n):
    return (x,) * n if isinstance(x, int) else tuple(x)


class _BaseConvRNNCell(RecurrentCell):
    """Shared conv-recurrence plumbing.  ``input_shape`` is (C, *spatial) —
    required up front (the reference has the same constraint: state shape
    depends on it)."""

    _num_gates = 1
    _activation = "tanh"

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dims=2, conv_layout="NCHW", activation="tanh",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._dims = dims
        self._activation = activation
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError("h2h_kernel dims must be odd (so the "
                                 "state keeps its spatial shape)")
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._i2h_dilate = _tuple(i2h_dilate, dims)
        self._h2h_dilate = _tuple(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        in_c = self._input_shape[0]
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(ng * hidden_channels, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ng * hidden_channels, hidden_channels)
                + self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_channels,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_channels,),
                init=h2h_bias_initializer)
        # spatial shape of the state: i2h conv output spatial dims
        spatial = []
        for i, s in enumerate(self._input_shape[1:]):
            k = self._i2h_kernel[i]
            d = self._i2h_dilate[i]
            p = self._i2h_pad[i]
            spatial.append((s + 2 * p - d * (k - 1) - 1) + 1)
        self._state_shape = (hidden_channels,) + tuple(spatial)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NC" + "DHW"[3 - self._dims:]}]

    def _conv(self, F, x, weight, bias, pad, dilate):
        return F.Convolution(x, weight, bias,
                             kernel=weight.shape[2:],
                             num_filter=weight.shape[0],
                             pad=pad, dilate=dilate)

    def _gates(self, F, x, h, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        i2h = self._conv(F, x, i2h_weight, i2h_bias, self._i2h_pad,
                         self._i2h_dilate)
        h2h = self._conv(F, h, h2h_weight, h2h_bias, self._h2h_pad,
                         self._h2h_dilate)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._gates(F, x, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_gates = 4

    def state_info(self, batch_size=0):
        shape = (batch_size,) + self._state_shape
        return [{"shape": shape, "__layout__": "NCHW"},
                {"shape": shape, "__layout__": "NCHW"}]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h, c = states
        i2h, h2h = self._gates(F, x, h, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(slices[0])
        f = F.sigmoid(slices[1])
        g = self._act(F, slices[2])
        o = F.sigmoid(slices[3])
        next_c = f * c + i * g
        next_h = o * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h = states[0]
        i2h, h2h = self._gates(F, x, h, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        i2h_s = F.split(i2h, num_outputs=3, axis=1)
        h2h_s = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_s[0] + h2h_s[0])
        update = F.sigmoid(i2h_s[1] + h2h_s[1])
        new = self._act(F, i2h_s[2] + reset * h2h_s[2])
        next_h = (1.0 - update) * new + update * h
        return next_h, [next_h]


def _make(base, dims, name):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 **kwargs):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, dims=dims, **kwargs)
    return type(name, (base,), {"__init__": __init__})


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
