"""Event handlers (reference
``gluon/contrib/estimator/event_handler.py``): mixin protocols
(Train/Epoch/Batch × Begin/End) + the stock handlers."""
from __future__ import annotations

import logging
import os
import time

import numpy as onp

from ....base import MXNetError


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch/max_batch (reference ``StoppingHandler``)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset train metrics at epoch begin, update at batch end."""

    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if getattr(m, "name", "").startswith("loss") and loss is not None:
                m.update(0, loss)
            elif pred is not None and label is not None:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every ``epoch_period`` epochs (reference
    ``ValidationHandler``)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Periodic metric logging (reference ``LoggingHandler``)."""

    def __init__(self, log_interval="epoch", metrics=None, priority=1000):
        if log_interval != "epoch" and not isinstance(log_interval, int):
            raise MXNetError("log_interval must be 'epoch' or int")
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.priority = priority
        self.logger = logging.getLogger("estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training done in %.3fs",
                         time.time() - self.train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0

    def epoch_end(self, estimator, *args, **kwargs):
        msgs = [f"{m.name}: {m.get()[1]:.4f}" for m in self.metrics]
        self.logger.info("Epoch[%d] time %.3fs %s", self.current_epoch,
                         time.time() - self.epoch_start, " ".join(msgs))
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msgs = [f"{m.name}: {m.get()[1]:.4f}" for m in self.metrics]
            self.logger.info("Epoch[%d] Batch[%d] %s", self.current_epoch,
                             self.batch_index, " ".join(msgs))


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params (+trainer states) per epoch; keep best by monitored
    metric (reference ``CheckpointHandler``)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.current_epoch = 0
        self.current_batch = 0
        self.saved = []
        if mode == "auto" and monitor is not None:
            mode = "max" if "acc" in getattr(monitor, "name", "") else "min"
        self.mode = mode
        self.best = -onp.inf if mode == "max" else onp.inf

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch}")
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            better = val > self.best if self.mode == "max" else val < self.best
            if better:
                self.best = val
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-{tag}.params")
        estimator.net.save_parameters(path)
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            if os.path.isfile(old):
                os.remove(old)


class AtomicCheckpointHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Periodic atomic checkpoints + auto-resume, wired to
    ``mx.checkpoint`` (ISSUE 15) — the preemption-safe successor of
    :class:`CheckpointHandler`'s epoch-boundary ``.params`` pattern.

    Every save is commit-or-invisible (temp dir + fsync + rename, CRC
    manifest) and captures the FULL training state — params, optimizer
    states/schedule counters, loss-scaler, RNG root key — plus the
    (epoch, batch) cursor as checkpoint ``extra``.  With
    ``resume=True`` (default), ``fit()`` restores the newest verifiable
    checkpoint at train begin (corrupt/incomplete ones are skipped with
    a ``checkpoint_corrupt`` event) and the handler's own counters pick
    up from the restored cursor; ``resumed_step`` reports what was
    loaded (None = fresh start).  Saves are step-indexed by the global
    batch count.
    """

    def __init__(self, directory, every_n_batches=None, every_n_epochs=1,
                 max_to_keep=5, async_save=True, resume=True,
                 priority=9000):
        if not directory:
            raise MXNetError("AtomicCheckpointHandler: directory required")
        self.directory = directory
        self.every_n_batches = every_n_batches
        self.every_n_epochs = every_n_epochs
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.resume = resume
        # run after the stock metric/logging handlers so a save sees
        # the batch fully applied
        self.priority = priority
        self.resumed_step = None
        self.current_batch = 0
        self.current_epoch = 0
        self._mgr = None

    def train_begin(self, estimator, *args, **kwargs):
        from .... import checkpoint as ckpt

        self._mgr = ckpt.CheckpointManager(
            self.directory, max_to_keep=self.max_to_keep,
            async_save=self.async_save)
        self.resumed_step = None
        self.current_batch = 0
        self.current_epoch = 0
        if not self.resume:
            return
        res = self._mgr.restore(estimator.net, estimator.trainer,
                                return_extra=True)
        if res is None:
            return
        step, extra = res
        self.resumed_step = step
        self.current_batch = int((extra or {}).get("batch", step))
        self.current_epoch = int((extra or {}).get("epoch", 0))

    def _save(self, estimator):
        self._mgr.save(self.current_batch, estimator.net,
                       estimator.trainer,
                       extra={"batch": self.current_batch,
                              "epoch": self.current_epoch})

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.every_n_batches and \
                self.current_batch % self.every_n_batches == 0:
            self._save(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.every_n_epochs and \
                self.current_epoch % self.every_n_epochs == 0:
            self._save(estimator)

    def train_end(self, estimator, *args, **kwargs):
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stalls (reference
    ``EarlyStoppingHandler``)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in getattr(monitor, "name", "") else "min"
        self.mode = mode
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        self.best = -onp.inf if self.mode == "max" else onp.inf

    def epoch_end(self, estimator, *args, **kwargs):
        _, val = self.monitor.get()
        improved = (val - self.min_delta > self.best) if self.mode == "max" \
            else (val + self.min_delta < self.best)
        if self.baseline is not None and not improved:
            improved = (val > self.baseline) if self.mode == "max" \
                else (val < self.baseline)
        if improved:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            logging.getLogger("estimator").info(
                "Early stopping at epoch %d", self.stopped_epoch)
