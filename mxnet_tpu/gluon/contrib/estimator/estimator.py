"""Estimator (reference ``gluon/contrib/estimator/estimator.py``):
``est.fit(train_data, val_data, epochs, event_handlers)`` — the high-level
fit loop with an event-handler system."""
from __future__ import annotations

import logging

from ....base import MXNetError
from .... import metric as metric_mod
from ... import Trainer
from ... import loss as loss_mod
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, LoggingHandler, ValidationHandler)


class Estimator:
    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None, device_prefetch=None,
                 fused_step=False):
        from .... import init as init_mod, context as ctx_mod
        self.net = net
        if not isinstance(loss, loss_mod.Loss):
            raise MXNetError("loss must be a gluon Loss")
        self.loss = loss
        metrics = metrics or []
        self.train_metrics = metrics if isinstance(metrics, list) \
            else [metrics]
        self.context = context or ctx_mod.current_context()
        self._device_prefetch = device_prefetch
        # opt-in fast path: when the net is hybridized, fit() runs each
        # batch through Trainer.fused_step — forward+loss+backward+
        # optimizer apply as ONE donated-buffer XLA dispatch instead of
        # the record/backward/step phase chain (MXNET_FUSED_STEP=0 or an
        # unsupported Trainer config falls back transparently)
        self._fused_step = bool(fused_step)
        if not self._net_initialized():
            self.net.initialize(initializer or init_mod.Xavier(),
                                ctx=self.context)
        self.trainer = trainer or Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 1e-3})
        self.val_metrics = [type(m)() for m in self.train_metrics]
        self.logger = logging.getLogger("estimator")

    def _net_initialized(self):
        for p in self.net.collect_params().values():
            if p._data is None and p._deferred_init is None:
                return False
        return True

    # ------------------------------------------------------------------ #
    def _prefetched(self, data):
        """One epoch's iterator over ``data``, routed through the
        device-prefetch ring when the estimator context is an accelerator:
        batch ``k+1``'s host load + H2D copy overlap step ``k``.  Inert on
        host contexts, when the loader already places on device
        (``DataLoader(device=...)``), or under ``MXNET_DEVICE_PREFETCH=0``
        — iteration then is exactly ``iter(data)``."""
        from ...data.dataloader import (DevicePrefetchIter,
                                        _resolve_device_prefetch)
        ctx = self.context
        if ctx is None or getattr(ctx, "device_type", "cpu").startswith("cpu"):
            return iter(data)
        if getattr(data, "_device", None) is not None:
            return iter(data)  # loader already device-aware
        depth = _resolve_device_prefetch(self._device_prefetch)
        if depth <= 0:
            return iter(data)
        return DevicePrefetchIter(iter(data), ctx, depth)

    def evaluate(self, val_data, batch_axis=0):
        for m in self.val_metrics:
            m.reset()
        for batch in self._prefetched(val_data):
            data, label = self._unpack(batch)
            pred = self.net(data)
            loss = self.loss(pred, label)
            for m in self.val_metrics:
                if getattr(m, "name", "").startswith("loss"):
                    m.update(0, loss)
                else:
                    m.update(label, pred)
        return [(m.name, m.get()[1]) for m in self.val_metrics]

    @staticmethod
    def _unpack(batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[0], batch[1]
        if hasattr(batch, "data"):
            return batch.data[0], batch.label[0]
        raise MXNetError("cannot unpack batch")

    def _sorted(self, handlers, kind):
        hs = [h for h in handlers if isinstance(h, kind)]
        return sorted(hs, key=lambda h: getattr(h, "priority", 0))

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        from .... import autograd
        if epochs is None and batches is None:
            raise MXNetError("fit: give epochs or batches")
        handlers = list(event_handlers or [])
        stopper = StoppingHandler(epochs, batches)
        handlers.append(stopper)
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))

        use_fused = (self._fused_step
                     and getattr(self.net, "_active", False)
                     and hasattr(self.trainer, "fused_step"))

        def _fused_loss(x, y):
            pred = self.net(x)
            return self.loss(pred, y), pred

        train_begin = self._sorted(handlers, TrainBegin)
        epoch_begin = self._sorted(handlers, EpochBegin)
        batch_begin = self._sorted(handlers, BatchBegin)
        batch_end = self._sorted(handlers, BatchEnd)
        epoch_end = self._sorted(handlers, EpochEnd)
        train_end = self._sorted(handlers, TrainEnd)

        for h in train_begin:
            h.train_begin(self)
        while not stopper.stop_training:
            for h in epoch_begin:
                h.epoch_begin(self)
            for batch in self._prefetched(train_data):
                data, label = self._unpack(batch)
                for h in batch_begin:
                    h.batch_begin(self, batch=batch)
                if use_fused:
                    loss, pred = self.trainer.fused_step(
                        _fused_loss, data, label,
                        batch_size=data.shape[batch_axis])
                else:
                    with autograd.record():
                        pred = self.net(data)
                        loss = self.loss(pred, label)
                    loss.backward()
                    self.trainer.step(data.shape[batch_axis])
                for h in batch_end:
                    h.batch_end(self, batch=batch, pred=pred, label=label,
                                loss=loss)
                if stopper.stop_training:
                    break
            for h in epoch_end:
                h.epoch_end(self)
            if hasattr(train_data, "reset"):
                train_data.reset()
            for h in [x for x in handlers
                      if getattr(x, "stop_training", False)]:
                stopper.stop_training = True
        for h in train_end:
            h.train_end(self)
        return self
