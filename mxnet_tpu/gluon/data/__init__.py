"""Gluon data API (reference ``python/mxnet/gluon/data/``; SURVEY.md §3.2
"Gluon data" row): Dataset/ArrayDataset/RecordFileDataset, samplers,
DataLoader, and ``vision`` (datasets + transforms)."""
from .dataset import (Dataset, SimpleDataset, ArrayDataset,
                      RecordFileDataset)
from .sampler import (Sampler, SequentialSampler, RandomSampler,
                      BatchSampler, IntervalSampler, FilterSampler)
from .dataloader import (DataLoader, DevicePrefetchIter, default_batchify_fn,
                         default_mp_batchify_fn)
from . import vision
from . import dataset
from . import sampler
from . import dataloader
from . import batchify
