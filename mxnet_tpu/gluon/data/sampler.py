"""Samplers (reference ``python/mxnet/gluon/data/sampler.py``)."""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError


class Sampler:
    """Abstract sampler: iterate over sample indices."""

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Shuffled indices.  With ``seed=None`` (default) each epoch draws
    from the process-global numpy RNG (reference behavior).  With a
    ``seed``, epoch ``e`` is the deterministic permutation of
    ``RandomState(seed + e)`` — the resumable-shuffle mode: after a
    restart, ``set_epoch(e)`` + a ``DataLoader.iter_from`` fast-forward
    reproduces exactly the batches the interrupted epoch would have
    yielded, without replaying data."""

    def __init__(self, length, seed=None):
        self._length = length
        self._seed = seed
        self._epoch = 0

    def set_epoch(self, epoch):
        """Position the seeded shuffle at ``epoch`` (the checkpoint
        data-cursor restore path; no-op ordering-wise when unseeded)."""
        self._epoch = int(epoch)

    def __iter__(self):
        if self._seed is None:
            indices = onp.random.permutation(self._length)
        else:
            rs = onp.random.RandomState(self._seed + self._epoch)
            indices = rs.permutation(self._length)
            self._epoch += 1
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class IntervalSampler(Sampler):
    """Samples i, i+interval, i+2*interval, ... for each start i (reference
    ``IntervalSampler``; used for distributed validation splits)."""

    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise MXNetError("interval must be <= length")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    """Indices of samples passing ``fn(dataset[i])``."""

    def __init__(self, fn, dataset):
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    """Group a sampler's indices into batches; ``last_batch`` in
    {'keep','discard','rollover'}."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(f"invalid last_batch {last_batch!r}")
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch

    def __len__(self):
        n = len(self._sampler) + len(self._prev)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        return n // self._batch_size
