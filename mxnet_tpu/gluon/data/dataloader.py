"""DataLoader (reference ``python/mxnet/gluon/data/dataloader.py``;
SURVEY.md §3.2 "Gluon data" row, §4.5 bottom).

TPU-native redesign of the worker model: the reference forks ``num_workers``
OS processes and ships NDArrays back over POSIX shared memory
(``cpu_shared()`` + ForkingPickler rebuild).  Forking a process that holds a
live TPU/XLA client is unsafe, and host→device transfer happens once per
batch anyway — so here ``num_workers`` maps onto a THREAD pool: sample
loading + JPEG decode (PIL/cv2/native C++) release the GIL, which is where
the reference's parallelism actually was, and batches are assembled into
host numpy before a single device put.  The queue/prefetch structure
(``prefetch`` batches in flight, ``pin_memory``≈host staging) matches the
reference's semantics; ``ConnectionWrapper``/shm plumbing is intentionally
absent because no process boundary exists.

Device prefetch (the TPU-native layer the reference never needed): with
``DataLoader(..., device=ctx, device_prefetch=N)`` batches come off the
iterator already RESIDENT on device — a :class:`DevicePrefetchIter` ring
keeps the H2D copies of batches ``k+1..k+N`` in flight while the caller
consumes batch ``k`` (``jax.device_put`` is async under XLA), so
steady-state step latency becomes ``max(host input, device compute)``
instead of their sum.  ``device`` also accepts a ``jax.sharding.Sharding``
or a device/context list: data-parallel runs get every batch landed
pre-sharded by ONE ``device_put`` (no per-replica host slicing in the
step).  ``MXNET_DEVICE_PREFETCH=0`` is the escape hatch back to the legacy
synchronous path (placement happens inline, bit-for-bit identical values).
"""
from __future__ import annotations

import itertools
import os
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor, TimeoutError as _FutTimeout

import numpy as onp

from ... import telemetry
from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray
from ...ndarray.ndarray import _placement_target, to_device
from .dataset import Dataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler


def default_batchify_fn(data):
    """Stack samples into batch NDArrays (reference ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0) if len(data) > 1 else \
            data[0].reshape((1,) + data[0].shape)
    if isinstance(data[0], (tuple, list)):
        return [default_batchify_fn(list(x)) for x in zip(*data)]
    arr = onp.asarray(data)
    if arr.dtype == onp.float64:
        arr = arr.astype(onp.float32)
    elif arr.dtype == onp.int64:
        arr = arr.astype(onp.int32)
    return nd.array(arr, dtype=str(arr.dtype))


# with no process boundary there is no separate shared-memory variant, but
# the reference name is part of the public surface
default_mp_batchify_fn = default_batchify_fn


def _env_device_prefetch(default=2):
    """``MXNET_DEVICE_PREFETCH``: default device-ring depth; ``0`` forces
    the legacy synchronous placement path everywhere (escape hatch)."""
    try:
        return int(os.environ.get("MXNET_DEVICE_PREFETCH", str(default)))
    except ValueError:
        return default


def _resolve_device_prefetch(depth):
    """Effective device-ring depth: ``MXNET_DEVICE_PREFETCH=0`` (the
    legacy-synchronous escape hatch) wins over any explicit argument;
    otherwise an explicit ``depth`` wins over the env default."""
    env = _env_device_prefetch()
    if env <= 0:
        return 0
    return max(0, int(depth)) if depth is not None else env


def _worker_load(dataset, batchify_fn, place_fn, indices):
    """One worker-thread batch: load samples, batchify, optionally place on
    device (the device-prefetch plumbing — H2D initiated right here in the
    pool thread, ``jax.device_put`` is async)."""
    samples = [dataset[i] for i in indices]
    batch = batchify_fn(samples)
    if place_fn is not None:
        batch = place_fn(batch)
    return batch


class _MultiWorkerIter:
    """Prefetching iterator: worker threads run ``dataset[idx]`` + batchify;
    results are delivered in order (reference ``_MultiWorkerIter``).

    ``prefetch`` is honored exactly as given (it bounds host memory — the
    ``2*num_workers`` default is applied by :class:`DataLoader` only when
    the user passed ``prefetch=None``).  ``timeout`` bounds the wait for
    any single batch; a stuck worker raises :class:`MXNetError` naming the
    batch index instead of hanging forever.  ``place_fn`` (set by the
    device-prefetch plumbing) runs as the last step of the worker-thread
    batchify so the thread pool feeds the device ring directly."""

    def __init__(self, dataset, batch_sampler, batchify_fn, num_workers,
                 prefetch, pin_memory, timeout=None, place_fn=None):
        self._dataset = dataset
        self._batchify_fn = batchify_fn
        self._batch_iter = iter(batch_sampler)
        self._executor = ThreadPoolExecutor(max_workers=num_workers)
        self._prefetch = max(1, prefetch)
        self._pending = deque()
        self._pin_memory = pin_memory
        self._timeout = timeout if timeout and timeout > 0 else None
        self._place_fn = place_fn
        self._batch_idx = 0
        self._closed = False
        # ring lock: ``next()`` (possibly on a training thread) races
        # ``shutdown()`` (``__del__`` runs on whatever thread drops the
        # last reference) — _pending/_closed/_batch_iter only move under
        # it.  RLock because _push_next is reached both ways.
        self._lock = threading.RLock()
        for _ in range(self._prefetch):
            self._push_next()

    def _push_next(self):
        with self._lock:
            if self._closed:
                return
            indices = next(self._batch_iter, None)
            if indices is None:
                return
            # module-level worker fn: queued work items must not hold a
            # reference back to this iterator, or an abandoned epoch's
            # __del__ cleanup never fires while batches are still queued
            self._pending.append(self._executor.submit(
                _worker_load, self._dataset, self._batchify_fn,
                self._place_fn, indices))

    def __iter__(self):
        return self

    def __next__(self):
        with self._lock:
            fut = self._pending.popleft() if self._pending else None
            if fut is not None:
                self._push_next()
        if fut is None:
            self.shutdown()
            raise StopIteration
        # the (possibly blocking) wait happens OUTSIDE the lock so a
        # concurrent shutdown() is never stuck behind a slow batch
        try:
            batch = fut.result(self._timeout)
        except _FutTimeout:
            idx = self._batch_idx
            self.shutdown()
            raise MXNetError(
                f"DataLoader worker timed out after {self._timeout}s "
                f"waiting for batch {idx}; raise DataLoader(timeout=...) if "
                f"your per-batch load legitimately takes longer") from None
        except BaseException:
            self.shutdown()
            raise
        self._batch_idx += 1
        return batch

    next = __next__

    def shutdown(self):
        """Cancel in-flight work and release the thread pool.  Safe to call
        repeatedly and from any thread (a concurrent ``next()`` either
        got its future out before the drain — and may see it cancelled —
        or finds the ring closed and stops); runs from ``__del__`` so an
        epoch abandoned mid-way (``break``) doesn't leak the executor or
        its futures."""
        # RLock, and every holder's critical section is short and
        # non-blocking: a GC-triggered __del__ on the holding thread
        # re-enters reentrantly, and one on another thread waits a
        # bounded few instructions — not the non-reentrant-accountant
        # deadlock TL012 exists for.
        # tracelint: disable=TL012 -- RLock + short non-blocking critical sections; finalizer re-entry is reentrant, cross-thread wait is bounded
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
        for fut in pending:
            fut.cancel()
        try:
            self._executor.shutdown(wait=False, cancel_futures=True)
        except TypeError:  # python < 3.9: no cancel_futures kwarg
            self._executor.shutdown(wait=False)

    close = shutdown

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


_END = object()  # device-prefetch producer's end-of-stream marker

# process-wide ring telemetry (ISSUE 9 train/data-pipeline wiring):
# depth gauge + consumer-stall counters.  A stall = the consumer asked
# for a batch the ring didn't have ready — the step is input-bound at
# that moment.  Lazy so importing the module never touches the registry.
_ring_tele_cache = None


def _ring_tele():
    global _ring_tele_cache
    if _ring_tele_cache is None:
        _ring_tele_cache = {
            "depth": telemetry.gauge("data_prefetch_ring_depth"),
            "stalls": telemetry.counter("data_prefetch_stalls_total"),
            "stall_s": telemetry.histogram(
                "data_prefetch_stall_seconds"),
        }
    return _ring_tele_cache


# memory-ledger identity for device-prefetch rings (``device_bytes{
# subsystem="data.prefetch_ring"}``) — monotonic so a closed ring's
# key is never reused
_ring_seq = itertools.count()


class DevicePrefetchIter:
    """Depth-``N`` device-resident prefetch ring over any batch iterator.

    While the caller consumes batch ``k``, batches ``k+1..k+N`` are already
    batchified with their host→device copies in flight (``jax.device_put``
    dispatches asynchronously), so steady-state step latency is
    ``max(input time, compute time)`` rather than their sum — the
    TPU-native analog of the reference's ``PrefetchingIter`` /
    ``dmlc::ThreadedIter``, extended to hide the H2D copy the reference
    never had to pay.

    ``device`` accepts a ``Context``, ``jax.Device``,
    ``jax.sharding.Sharding``, or a list of contexts/devices (one
    ``device_put`` with a batch-axis ``NamedSharding`` lands each device's
    slice pre-sharded for data-parallel step loops).

    Pump modes:

    * ``background=True`` (default; right for same-process sources): a
      producer thread pulls from ``source`` and places, so host batchify
      itself also overlaps the training step.
    * ``background=False`` (used over :class:`_MultiWorkerIter`, whose
      thread pool already batchifies ahead): threadless ring — each
      ``__next__`` pulls one completed host batch from the pool,
      initiates its async transfer, and returns the batch whose transfer
      was initiated ``N`` calls earlier.

    ``depth=0`` (or ``MXNET_DEVICE_PREFETCH=0``) degenerates to the legacy
    synchronous path: pull + place inline, no ring, no thread — values are
    bit-for-bit identical, only the overlap disappears.
    """

    def __init__(self, source, device, depth=None, background=True):
        self._source = iter(source)
        self._target = _placement_target(device)
        self._depth = _resolve_device_prefetch(depth)
        self._ring = deque()
        self._exhausted = False
        self._done = False
        self._queue = None
        self._thread = None
        self._stop = threading.Event()
        self._err = None
        # guards _ring/_done/_exhausted: ``close()`` runs from __del__
        # on whatever thread drops the last reference while ``next()``
        # may still be mid-pull on the training thread
        self._lock = threading.RLock()
        # memory-accountant entry: the ring's device footprint is
        # registered as depth x per-batch bytes (the full-ring upper
        # bound) and only re-registered when the batch size actually
        # changes — steady-state epochs cost one dict compare per batch
        self._mem_key = f"ring{next(_ring_seq)}"
        self._batch_pd = None
        self._background = bool(background) and self._depth > 0
        if self._background:
            self._queue = _queue.Queue(maxsize=self._depth)
            self._thread = threading.Thread(
                target=self._produce, name="mx-device-prefetch", daemon=True)
            self._thread.start()

    # -- placement ------------------------------------------------------- #
    def _place(self, batch):
        if self._target is None:
            return batch
        placed = to_device(batch, self._target)
        self._account(placed)
        return placed

    def _account(self, placed):
        """Keep the ``data.prefetch_ring`` ledger entry at depth x
        per-batch device bytes (the ring's full-depth upper bound —
        transfers in flight count as resident, which is exactly the
        budget question).  Runs on whichever thread places (producer or
        consumer); the last-seen size is compared under ``_lock``."""
        from ...telemetry.memory import ACCOUNTANT, per_device_bytes

        pd = per_device_bytes(placed)
        with self._lock:
            if pd == self._batch_pd:
                return
            self._batch_pd = pd
        depth = max(self._depth, 1)
        ACCOUNTANT.set("data.prefetch_ring", self._mem_key,
                       per_device={d: b * depth for d, b in pd.items()})

    # -- background producer --------------------------------------------- #
    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _produce(self):
        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                if not self._put(self._place(batch)):
                    return
        except BaseException as e:  # deliver to the consumer thread
            self._err = e
        self._put(_END)

    # -- iterator protocol ----------------------------------------------- #
    def __iter__(self):
        return self

    def __next__(self):
        if self._background:
            with self._lock:
                if self._done:  # the single _END was already consumed —
                    raise StopIteration  # next() must not block forever
            # blocking get is safe: the producer always delivers _END
            # (even on error), and close() injects one after the join
            # so a consumer parked here wakes instead of hanging
            tele = _ring_tele()
            tele["depth"].set(self._queue.qsize())
            if self._queue.empty():
                tele["stalls"].inc()
                t0 = time.perf_counter()
                item = self._queue.get()
                tele["stall_s"].observe(time.perf_counter() - t0)
            else:
                item = self._queue.get()
            if item is _END:
                with self._lock:
                    self._done = True
                # rebroadcast the pill so any OTHER consumer parked in
                # queue.get() wakes too (later calls stop at _done).
                # Dropping on Full is safe HERE: a full queue means no
                # consumer is parked, and _done is already set above
                try:
                    self._queue.put_nowait(_END)
                except _queue.Full:
                    pass
                if self._err is not None:
                    err, self._err = self._err, None
                    raise err
                raise StopIteration
            return item
        if self._depth == 0:  # legacy synchronous path
            return self._place(next(self._source))
        # threadless ring over an already-asynchronous source; the pull
        # (which may block on the wrapped pool) stays outside the lock
        tele = _ring_tele()
        with self._lock:
            if not self._ring and not self._exhausted:
                tele["stalls"].inc()   # transfers not ahead of consume
        while True:
            with self._lock:
                if len(self._ring) >= self._depth or self._exhausted:
                    break
            try:
                item = self._place(next(self._source))
            except StopIteration:
                with self._lock:
                    self._exhausted = True
                break
            with self._lock:
                self._ring.append(item)
        with self._lock:
            tele["depth"].set(len(self._ring))
            if not self._ring:
                raise StopIteration
            return self._ring.popleft()

    next = __next__

    def close(self):
        """Stop the producer and release the source (cancels a wrapped
        ``_MultiWorkerIter``'s pool).  Called from ``__del__`` so breaking
        out of an epoch cleans up both layers; safe against a consumer
        concurrently blocked in ``next()``."""
        self._stop.set()
        if self._thread is not None:
            # drain so a producer stuck on a full queue exits its put
            # loop promptly (it re-checks _stop every 50 ms regardless)
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=5)
            self._thread = None
            # the producer skips its end-of-stream marker once _stop is
            # set — inject one so a consumer parked in queue.get() wakes.
            # A straggler batch may have landed in the drained slot
            # before the producer noticed _stop; the producer is dead
            # after the join, so evicting and retrying must terminate
            # and the pill is GUARANTEED to land (a dropped pill means a
            # consumer blocks forever).
            while True:
                try:
                    self._queue.put_nowait(_END)
                    break
                except _queue.Full:
                    try:
                        self._queue.get_nowait()
                    except _queue.Empty:
                        pass
        # tracelint: disable=TL012 -- RLock + short non-blocking critical sections; finalizer re-entry is reentrant, cross-thread wait is bounded
        with self._lock:
            self._ring.clear()
        from ...telemetry.memory import ACCOUNTANT

        # deferred: close() runs from __del__, and a GC-triggered
        # finalizer may fire inside a thread already holding the
        # accountant lock — it must never take it synchronously
        ACCOUNTANT.drop_deferred("data.prefetch_ring", self._mem_key)
        for attr in ("shutdown", "close"):
            fn = getattr(self._source, attr, None)
            if callable(fn):
                try:
                    fn()
                except Exception:
                    pass
                break

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DataLoader:
    """Load a ``Dataset`` in mini-batches (reference ``gluon.data.DataLoader``
    API: sampler/batch_sampler/shuffle/last_batch/num_workers/batchify_fn/
    pin_memory/prefetch/timeout) plus the TPU-native device-prefetch layer
    (``device=``/``device_prefetch=`` — see :class:`DevicePrefetchIter`)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120, device=None,
                 device_prefetch=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._prefetch = max(1, prefetch) if prefetch is not None \
            else 2 * self._num_workers
        self._timeout = timeout
        self._device = device
        self._device_prefetch = device_prefetch

        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch are "
                             "mutually exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn

    def _sampler_batches(self, skip=0):
        """The epoch's index-batch stream, fast-forwarded past the
        first ``skip`` batches: their indices are DRAWN from the
        sampler (so a seeded shuffle's position advances exactly as if
        they had been consumed) but no sample is ever loaded,
        batchified, or placed — the checkpoint data-cursor restore
        path.  Each yielded batch is a ``data.next`` fault-injection
        site (``MXNET_FAULT_INJECT``)."""
        it = iter(self._batch_sampler)
        for k in range(skip):
            try:
                next(it)
            except StopIteration:
                raise MXNetError(
                    f"iter_from({skip}): the sampler yields only {k} "
                    "batches this epoch — the resume cursor is past "
                    "the end of the data") from None

        def _gen():
            for i, batch in enumerate(it, start=skip):
                telemetry.fault_point("data.next", batch=i)
                yield batch
        return _gen()

    def __iter__(self):
        return self._make_iter(self._sampler_batches())

    def iter_from(self, batches_done):
        """One epoch's iterator resumed mid-epoch: identical to
        ``iter(loader)`` with the first ``batches_done`` batches
        skipped at the SAMPLER level (indices drawn, data never
        loaded).  With a seeded :class:`RandomSampler` positioned via
        ``set_epoch``, this reproduces the interrupted epoch's
        remaining batches exactly — the restore half of the
        checkpointed data cursor.  ``last_batch='rollover'`` refuses:
        its carried-over ``_prev`` indices are in-memory state a
        restarted process cannot reconstruct, so epochs past the first
        would resume with silently shifted batch boundaries."""
        if getattr(self._batch_sampler, "_last_batch", None) == \
                "rollover":
            raise MXNetError(
                "iter_from: last_batch='rollover' carries leftover "
                "indices across epochs in process memory, which a "
                "resume cannot reconstruct — bit-exact mid-epoch "
                "resume needs last_batch='keep' or 'discard'")
        return self._make_iter(self._sampler_batches(int(batches_done)))

    def iter_shard(self, batches_done, num_shards=1, shard_id=0):
        """Elastic pod re-bucketing of ONE shared batch stream: resume
        the epoch at global batch ``batches_done`` and serve only the
        batches owned by ``shard_id`` — global batch ``g`` belongs to
        rank ``(g - batches_done) % num_shards``, so a pod of W ranks
        stepping in lockstep consumes W consecutive global batches per
        step.  Foreign shards' indices are DRAWN (the seeded sampler
        position advances identically on every rank) but never loaded,
        batchified, or placed.  Because ownership is a pure function of
        ``(g, batches_done, num_shards)``, a pod that checkpoints its
        global-batch cursor and resumes on a DIFFERENT rank count
        re-buckets deterministically: the union of all ranks' streams
        is exactly the remaining batches, in order, each served once —
        no sample re-served, none skipped.  Same ``last_batch``
        restrictions as :meth:`iter_from`."""
        num_shards = int(num_shards)
        shard_id = int(shard_id)
        if num_shards < 1 or not (0 <= shard_id < num_shards):
            raise MXNetError(
                f"iter_shard: shard_id {shard_id} out of range for "
                f"{num_shards} shard(s)")
        if num_shards == 1:
            return self.iter_from(batches_done)
        if getattr(self._batch_sampler, "_last_batch", None) == \
                "rollover":
            raise MXNetError(
                "iter_shard: last_batch='rollover' carries leftover "
                "indices across epochs in process memory, which a "
                "resume cannot reconstruct — bit-exact mid-epoch "
                "resume needs last_batch='keep' or 'discard'")
        batches_done = int(batches_done)
        it = iter(self._batch_sampler)
        for k in range(batches_done):
            try:
                next(it)
            except StopIteration:
                raise MXNetError(
                    f"iter_shard({batches_done}): the sampler yields "
                    f"only {k} batches this epoch — the resume cursor "
                    "is past the end of the data") from None

        def _gen():
            for g, batch in enumerate(it, start=batches_done):
                if (g - batches_done) % num_shards != shard_id:
                    continue
                telemetry.fault_point("data.next", batch=g)
                yield batch
        return self._make_iter(_gen())

    def set_epoch(self, epoch):
        """Forward the epoch position to samplers that support it
        (seeded :class:`RandomSampler` — the resume path)."""
        for obj in (self._batch_sampler,
                    getattr(self._batch_sampler, "_sampler", None)):
            fn = getattr(obj, "set_epoch", None)
            if callable(fn):
                fn(epoch)

    def _make_iter(self, batch_iter):
        if self._num_workers == 0:
            def _same_process_iter():
                for batch in batch_iter:
                    yield self._batchify_fn([self._dataset[i] for i in batch])
            base = _same_process_iter()
            if self._device is None:
                return base
            # background producer: host batchify AND the H2D copy both
            # overlap the consumer's step
            return DevicePrefetchIter(base, self._device,
                                      self._device_prefetch, background=True)
        place_fn = None
        depth = _resolve_device_prefetch(self._device_prefetch)
        if self._device is not None and depth >= self._prefetch:
            # the device ring is at least as deep as the host prefetch
            # bound, so every in-flight batch may be device-resident:
            # place inside the worker thread — H2D is initiated the
            # moment batchify finishes, no extra layer
            target = _placement_target(self._device)
            place_fn = lambda batch: to_device(batch, target)  # noqa: E731
        it = _MultiWorkerIter(self._dataset, batch_iter,
                              self._batchify_fn, self._num_workers,
                              self._prefetch, self._pin_memory,
                              timeout=self._timeout, place_fn=place_fn)
        if self._device is None or place_fn is not None:
            return it
        # the worker pool already batchifies ahead — the threadless ring
        # pulls completed host batches straight into the device ring
        # (depth 0 = MXNET_DEVICE_PREFETCH=0 = synchronous placement)
        return DevicePrefetchIter(it, self._device, depth, background=False)

    def __len__(self):
        return len(self._batch_sampler)
