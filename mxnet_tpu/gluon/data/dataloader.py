"""DataLoader (reference ``python/mxnet/gluon/data/dataloader.py``;
SURVEY.md §3.2 "Gluon data" row, §4.5 bottom).

TPU-native redesign of the worker model: the reference forks ``num_workers``
OS processes and ships NDArrays back over POSIX shared memory
(``cpu_shared()`` + ForkingPickler rebuild).  Forking a process that holds a
live TPU/XLA client is unsafe, and host→device transfer happens once per
batch anyway — so here ``num_workers`` maps onto a THREAD pool: sample
loading + JPEG decode (PIL/cv2/native C++) release the GIL, which is where
the reference's parallelism actually was, and batches are assembled into
host numpy before a single device put.  The queue/prefetch structure
(``prefetch`` batches in flight, ``pin_memory``≈host staging) matches the
reference's semantics; ``ConnectionWrapper``/shm plumbing is intentionally
absent because no process boundary exists.
"""
from __future__ import annotations

import queue as _queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray
from .dataset import Dataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler


def default_batchify_fn(data):
    """Stack samples into batch NDArrays (reference ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0) if len(data) > 1 else \
            data[0].reshape((1,) + data[0].shape)
    if isinstance(data[0], (tuple, list)):
        return [default_batchify_fn(list(x)) for x in zip(*data)]
    arr = onp.asarray(data)
    if arr.dtype == onp.float64:
        arr = arr.astype(onp.float32)
    elif arr.dtype == onp.int64:
        arr = arr.astype(onp.int32)
    return nd.array(arr, dtype=str(arr.dtype))


# with no process boundary there is no separate shared-memory variant, but
# the reference name is part of the public surface
default_mp_batchify_fn = default_batchify_fn


class _MultiWorkerIter:
    """Prefetching iterator: worker threads run ``dataset[idx]`` + batchify;
    results are delivered in order (reference ``_MultiWorkerIter``)."""

    def __init__(self, dataset, batch_sampler, batchify_fn, num_workers,
                 prefetch, pin_memory):
        self._dataset = dataset
        self._batchify_fn = batchify_fn
        self._batch_iter = iter(batch_sampler)
        self._executor = ThreadPoolExecutor(max_workers=num_workers)
        self._prefetch = max(prefetch, 2 * num_workers)
        self._pending = []
        self._pin_memory = pin_memory
        for _ in range(self._prefetch):
            self._push_next()

    def _load_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def _push_next(self):
        indices = next(self._batch_iter, None)
        if indices is None:
            return
        self._pending.append(self._executor.submit(self._load_batch, indices))

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            self._executor.shutdown(wait=False)
            raise StopIteration
        fut = self._pending.pop(0)
        self._push_next()
        return fut.result()

    next = __next__


class DataLoader:
    """Load a ``Dataset`` in mini-batches (reference ``gluon.data.DataLoader``
    API: sampler/batch_sampler/shuffle/last_batch/num_workers/batchify_fn/
    pin_memory/prefetch/timeout)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch) if prefetch is not None \
            else 2 * self._num_workers
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch are "
                             "mutually exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def _same_process_iter():
                for batch in self._batch_sampler:
                    yield self._batchify_fn([self._dataset[i] for i in batch])
            return _same_process_iter()
        return _MultiWorkerIter(self._dataset, self._batch_sampler,
                                self._batchify_fn, self._num_workers,
                                self._prefetch, self._pin_memory)

    def __len__(self):
        return len(self._batch_sampler)
