"""Vision datasets (reference
``python/mxnet/gluon/data/vision/datasets.py``).

No-network environment: ``pretrained``-style auto-download is disabled;
datasets read the standard on-disk formats from ``root`` (MNIST idx files,
CIFAR binary batches) and raise a clear error when absent.  A
``synthetic=N`` escape hatch generates deterministic fake data with the real
shapes/dtypes so training-loop tests and benchmarks run hermetically (the
role the reference's ``--benchmark 1`` dummy iterators play, SURVEY.md §6).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as onp

from ....base import MXNetError
from .... import ndarray as nd
from ..dataset import Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform, synthetic=0):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        if synthetic:
            self._make_synthetic(synthetic)
        else:
            self._get_data()

    def _make_synthetic(self, n):
        """Deterministic LEARNABLE synthetic data: each class is a fixed
        smooth prototype image (shared between train/test splits via a
        fixed seed) observed under random shift / brightness / pixel noise
        (per-split seed).  A ConvNet that learns shift-robust class
        structure generalizes to the test split, so synthetic-mode
        accuracy is a real signal — this backs the accuracy-parity gate
        when the sandbox has no dataset egress (BASELINE.md config 1)."""
        sample_rng = onp.random.RandomState(42 if self._train else 43)
        shape = self._synthetic_shape()
        ncls = self._num_classes()
        proto_rng = onp.random.RandomState(7)
        protos = proto_rng.rand(ncls, *shape).astype(onp.float32)
        for ax in (1, 2):                    # blur for spatial coherence
            for _ in range(2):
                protos = (onp.roll(protos, 1, axis=ax) + protos +
                          onp.roll(protos, -1, axis=ax)) / 3.0
        protos = (protos - protos.min()) / (onp.ptp(protos) + 1e-9) * 255
        labels = sample_rng.randint(0, ncls, size=(n,)).astype(onp.int32)
        data = onp.empty((n,) + tuple(shape), onp.uint8)
        chunk = 8192
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            blk = protos[labels[lo:hi]]
            dy = sample_rng.randint(-3, 4, size=hi - lo)
            dx = sample_rng.randint(-3, 4, size=hi - lo)
            for sy in range(-3, 4):
                for sx in range(-3, 4):
                    m = (dy == sy) & (dx == sx)
                    if m.any():
                        blk[m] = onp.roll(blk[m], (sy, sx), axis=(1, 2))
            bright = 0.7 + 0.6 * sample_rng.rand(
                hi - lo, 1, 1, 1).astype(onp.float32)
            noise = sample_rng.randn(hi - lo, *shape).astype(
                onp.float32) * 16.0
            data[lo:hi] = onp.clip(blk * bright + noise, 0,
                                   255).astype(onp.uint8)
        self._data = data
        self._label = labels

    def _synthetic_shape(self):
        raise NotImplementedError

    def _num_classes(self):
        return 10

    def _get_data(self):
        raise NotImplementedError

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        x = nd.array(self._data[idx], dtype="uint8")
        y = int(self._label[idx])
        if self._transform is not None:
            return self._transform(x, y)
        return x, y


class MNIST(_DownloadedDataset):
    """MNIST from idx(.gz) files in ``root`` (reference layout:
    train-images-idx3-ubyte.gz etc.)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None, synthetic=0):
        super().__init__(root, train, transform, synthetic)

    def _synthetic_shape(self):
        return (28, 28, 1)

    @staticmethod
    def _read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, = struct.unpack(">I", f.read(4))
            ndim = magic & 0xFF
            dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
            return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(dims)

    def _find(self, base):
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.isfile(p):
                return p
        raise MXNetError(
            f"{base}(.gz) not found under {self._root}; downloads are "
            f"disabled in this environment — place the files there or use "
            f"synthetic=N")

    def _get_data(self):
        img_f, lbl_f = self._train_files if self._train else self._test_files
        imgs = self._read_idx(self._find(img_f))
        self._data = imgs[:, :, :, None]
        self._label = self._read_idx(self._find(lbl_f)).astype(onp.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None, synthetic=0):
        super().__init__(root, train, transform, synthetic)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python pickle batches or binary ``.bin`` format."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None, synthetic=0):
        super().__init__(root, train, transform, synthetic)

    def _synthetic_shape(self):
        return (32, 32, 3)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        # python-pickle layout (cifar-10-batches-py)
        pydir = os.path.join(self._root, "cifar-10-batches-py")
        if os.path.isdir(pydir):
            data, labels = [], []
            for b in self._batches():
                with open(os.path.join(pydir, b), "rb") as f:
                    d = pickle.load(f, encoding="latin1")
                data.append(onp.asarray(d["data"], dtype=onp.uint8))
                labels.extend(d["labels"])
            raw = onp.concatenate(data).reshape(-1, 3, 32, 32)
            self._data = raw.transpose(0, 2, 3, 1)
            self._label = onp.asarray(labels, dtype=onp.int32)
            return
        # binary layout (cifar-10-batches-bin): 1 label byte + 3072 img bytes
        bindir = os.path.join(self._root, "cifar-10-batches-bin")
        names = [f"{b}.bin" for b in self._batches()]
        if os.path.isdir(bindir) or all(
                os.path.isfile(os.path.join(self._root, n)) for n in names):
            base = bindir if os.path.isdir(bindir) else self._root
            recs = []
            for n in names:
                with open(os.path.join(base, n), "rb") as f:
                    recs.append(onp.frombuffer(f.read(), dtype=onp.uint8)
                                .reshape(-1, 3073))
            raw = onp.concatenate(recs)
            self._label = raw[:, 0].astype(onp.int32)
            self._data = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return
        raise MXNetError(
            f"CIFAR10 data not found under {self._root}; downloads are "
            f"disabled — place cifar-10-batches-py/ or *.bin there, or use "
            f"synthetic=N")


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=True, train=True, transform=None, synthetic=0):
        self._fine = fine_label
        super().__init__(root, train, transform, synthetic)

    def _num_classes(self):
        return 100 if self._fine else 20

    def _get_data(self):
        pydir = os.path.join(self._root, "cifar-100-python")
        if not os.path.isdir(pydir):
            raise MXNetError(
                f"CIFAR100 data not found under {self._root}; use synthetic=N")
        name = "train" if self._train else "test"
        with open(os.path.join(pydir, name), "rb") as f:
            d = pickle.load(f, encoding="latin1")
        raw = onp.asarray(d["data"], dtype=onp.uint8).reshape(-1, 3, 32, 32)
        self._data = raw.transpose(0, 2, 3, 1)
        key = "fine_labels" if self._fine else "coarse_labels"
        self._label = onp.asarray(d[key], dtype=onp.int32)


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file: samples are (image NDArray,
    label) decoded from packed IRHeader records."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio
        from ....image import imdecode
        record = self._record[idx]
        header, img = recordio.unpack(record)
        label = header.label
        if hasattr(label, "size") and getattr(label, "size", 1) == 1:
            label = float(onp.asarray(label).reshape(-1)[0])
        x = imdecode(img, iscolor=self._flag)
        if self._transform is not None:
            return self._transform(x, label)
        return x, label


class ImageFolderDataset(Dataset):
    """``root/category/image.jpg`` folder layout (reference
    ``ImageFolderDataset``)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = (".jpg", ".jpeg", ".png", ".bmp")
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(self._exts):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from ....image import imread
        fname, label = self.items[idx]
        img = imread(fname, iscolor=self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageListDataset(Dataset):
    """Dataset from an explicit (path, label) list."""

    def __init__(self, root=".", imglist=None, flag=1):
        self._root = root
        self._flag = flag
        self.items = list(imglist or [])

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from ....image import imread
        fname, label = self.items[idx]
        return imread(os.path.join(self._root, fname), iscolor=self._flag), label
