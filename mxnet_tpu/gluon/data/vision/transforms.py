"""Vision transforms (reference
``python/mxnet/gluon/data/vision/transforms.py``; SURVEY.md §3.2).

Transforms are Blocks (composable with ``Compose``, usable via
``dataset.transform_first``).  Geometric/color transforms run on host numpy
(they execute in DataLoader workers, before device transfer); ``ToTensor``/
``Normalize`` are pure array math and also work on device data.
"""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from ....base import MXNetError
from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "CropResize",
           "RandomFlipLeftRight", "RandomFlipTopBottom", "RandomBrightness",
           "RandomContrast", "RandomSaturation", "RandomHue",
           "RandomColorJitter", "RandomLighting", "RandomGray"]


class Compose(Sequential):
    """Sequentially compose transforms (hybridizes contiguous HybridBlocks
    in the reference; here composition is plain sequencing)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class _HostTransform(Block):
    """Host-side transform base: __call__(x [, label]) passthrough."""

    def __call__(self, x, *args):
        out = self.forward(x)
        return (out,) + args if args else out


class Cast(_HostTransform):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(_HostTransform):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference ``ToTensor``)."""

    def forward(self, x):
        arr = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return nd.array(arr.astype(onp.float32) / 255.0)


class Normalize(_HostTransform):
    """Channel-wise (x - mean) / std on CHW float input."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype=onp.float32).reshape(-1, 1, 1)
        self._std = onp.asarray(std, dtype=onp.float32).reshape(-1, 1, 1)

    def forward(self, x):
        arr = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
        return nd.array((arr - self._mean) / self._std)


class Resize(_HostTransform):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        from ....image import imresize, resize_short
        if isinstance(self._size, int):
            if self._keep:
                return resize_short(x, self._size, self._interp)
            return imresize(x, self._size, self._size, self._interp)
        return imresize(x, self._size[0], self._size[1], self._interp)


class CenterCrop(_HostTransform):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x):
        from ....image import center_crop
        return center_crop(x, self._size, self._interp)[0]


class RandomResizedCrop(_HostTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4.0, 4 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        from ....image import random_size_crop
        return random_size_crop(x, self._size, self._scale, self._ratio,
                                self._interp)[0]


class CropResize(_HostTransform):
    def __init__(self, x0, y0, width, height, size=None, interpolation=1):
        super().__init__()
        self._args = (x0, y0, width, height)
        self._size = size
        self._interp = interpolation

    def forward(self, x):
        from ....image import fixed_crop
        return fixed_crop(x, *self._args, size=self._size,
                          interp=self._interp)


class RandomFlipLeftRight(_HostTransform):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if pyrandom.random() < self._p:
            arr = x.asnumpy()
            return nd.array(arr[:, ::-1].copy(), dtype=str(arr.dtype))
        return x


class RandomFlipTopBottom(_HostTransform):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if pyrandom.random() < self._p:
            arr = x.asnumpy()
            return nd.array(arr[::-1].copy(), dtype=str(arr.dtype))
        return x


class RandomBrightness(_HostTransform):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        from ....image import BrightnessJitterAug
        return BrightnessJitterAug(self._b)(x)


class RandomContrast(_HostTransform):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        from ....image import ContrastJitterAug
        return ContrastJitterAug(self._c)(x)


class RandomSaturation(_HostTransform):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        from ....image import SaturationJitterAug
        return SaturationJitterAug(self._s)(x)


class RandomHue(_HostTransform):
    """Hue jitter via RGB rotation approximation (reference uses the same
    YIQ-space trick)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        alpha = pyrandom.uniform(-self._h, self._h)
        arr = (x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)).astype(onp.float32)
        u, w = onp.cos(alpha * onp.pi), onp.sin(alpha * onp.pi)
        t_yiq = onp.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], dtype=onp.float32)
        t_rgb = onp.array([[1.0, 0.956, 0.621],
                           [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]], dtype=onp.float32)
        rot = onp.array([[1, 0, 0], [0, u, -w], [0, w, u]], dtype=onp.float32)
        m = t_rgb @ rot @ t_yiq
        return nd.array(arr @ m.T)


class RandomColorJitter(_HostTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._augs = []
        if brightness:
            self._augs.append(RandomBrightness(brightness))
        if contrast:
            self._augs.append(RandomContrast(contrast))
        if saturation:
            self._augs.append(RandomSaturation(saturation))
        if hue:
            self._augs.append(RandomHue(hue))

    def forward(self, x):
        augs = list(self._augs)
        pyrandom.shuffle(augs)
        for a in augs:
            x = a(x)
        return x


class RandomLighting(_HostTransform):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        from ....image import LightingAug
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        return LightingAug(self._alpha, eigval, eigvec)(x)


class RandomGray(_HostTransform):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if pyrandom.random() < self._p:
            arr = (x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)).astype(onp.float32)
            gray = (arr * onp.array([0.299, 0.587, 0.114], dtype=onp.float32)).sum(
                axis=-1, keepdims=True)
            return nd.array(onp.repeat(gray, 3, axis=-1))
        return x
