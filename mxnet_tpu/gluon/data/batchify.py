"""Batchify functions (reference ``gluon/data/batchify.py`` /
GluonNLP ``nlp.data.batchify``): Stack, Pad, Tuple/Group — composable
``batchify_fn``s for DataLoader, the variable-length-sequence batching
surface that feeds BucketingModule-style training."""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class Stack:
    """Stack equal-shape samples into a batch tensor."""

    def __call__(self, data):
        arrs = [_as_np(d) for d in data]
        out = onp.stack(arrs)
        if out.dtype == onp.float64:
            out = out.astype(onp.float32)
        if out.dtype == onp.int64:
            out = out.astype(onp.int32)
        return nd.array(out, dtype=str(out.dtype))


class Pad:
    """Pad variable-length samples to the batch max along ``axis``
    (reference ``Pad``): optionally also return the valid lengths."""

    def __init__(self, axis=0, pad_val=0, ret_length=False, dtype=None):
        self._axis = axis
        self._pad_val = pad_val
        self._ret_length = ret_length
        self._dtype = dtype

    def __call__(self, data):
        arrs = [_as_np(d) for d in data]
        lengths = onp.array([a.shape[self._axis] for a in arrs],
                            dtype=onp.int32)
        max_len = int(lengths.max())
        padded = []
        for a in arrs:
            pad_width = [(0, 0)] * a.ndim
            pad_width[self._axis] = (0, max_len - a.shape[self._axis])
            padded.append(onp.pad(a, pad_width, constant_values=self._pad_val))
        out = onp.stack(padded)
        if self._dtype:
            out = out.astype(self._dtype)
        elif out.dtype == onp.float64:
            out = out.astype(onp.float32)
        elif out.dtype == onp.int64:
            out = out.astype(onp.int32)
        batch = nd.array(out, dtype=str(out.dtype))
        if self._ret_length:
            return batch, nd.array(lengths, dtype="int32")
        return batch


class Tuple:
    """Apply one batchify fn per sample field: ``Tuple(Pad(), Stack())``."""

    def __init__(self, *fns):
        if len(fns) == 1 and isinstance(fns[0], (list, tuple)):
            fns = tuple(fns[0])
        self._fns = fns

    def __call__(self, data):
        if len(data[0]) != len(self._fns):
            raise MXNetError(
                f"Tuple batchify: sample has {len(data[0])} fields but "
                f"{len(self._fns)} fns were given")
        return tuple(fn([sample[i] for sample in data])
                     for i, fn in enumerate(self._fns))


Group = Tuple  # reference alias


class List:
    """Return the samples as a plain python list (no batching)."""

    def __call__(self, data):
        return list(data)
