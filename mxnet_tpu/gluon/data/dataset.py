"""Dataset classes (reference ``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray


class Dataset:
    """Abstract dataset: ``__getitem__`` + ``__len__`` (reference
    ``gluon.data.Dataset``)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        """Return a dataset with only samples for which ``fn(sample)`` is
        truthy (materializes the index list, like the reference)."""
        indices = [i for i in range(len(self)) if fn(self[i])]
        return _SampledDataset(self, indices)

    def shard(self, num_shards, index):
        """Every ``num_shards``-th sample starting at ``index`` (for
        data-parallel hosts)."""
        if not 0 <= index < num_shards:
            raise MXNetError("shard index out of range")
        indices = list(range(index, len(self), num_shards))
        return _SampledDataset(self, indices)

    def take(self, count):
        count = min(count, len(self))
        return _SampledDataset(self, list(range(count)))

    def sample(self, sampler):
        return _SampledDataset(self, list(sampler))

    def transform(self, fn, lazy=True):
        """Return a dataset whose samples are ``fn(*sample)``."""
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        """Apply ``fn`` to the first element of each sample only (the usual
        image-transform entry point)."""
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _SampledDataset(Dataset):
    def __init__(self, data, indices):
        self._data = data
        self._indices = list(indices)

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._data[self._indices[idx]]


class SimpleDataset(Dataset):
    """Wrap any sized, indexable object."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip multiple equal-length arrays/datasets into (a, b, ...) samples."""

    def __init__(self, *args):
        if not args:
            raise MXNetError("ArrayDataset needs at least one array")
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            if len(data) != self._length:
                raise MXNetError(f"all arrays must have the same length; "
                                 f"arg {i} has {len(data)} != {self._length}")
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file (``.rec`` + ``.idx``);
    reference ``gluon.data.RecordFileDataset``.  Reads go through the
    native C++ reader (offset-indexed pread, thread-safe) when the
    ``mxtpu_io`` library is available."""

    def __init__(self, filename):
        from ... import recordio, _native
        self._filename = filename
        idx_file = filename[:-4] + ".idx" if filename.endswith(".rec") \
            else filename + ".idx"
        self._native = None
        if _native.available():
            try:
                import os as _os
                self._native = _native.NativeRecordReader(
                    filename, idx_file if _os.path.isfile(idx_file) else "")
                return
            except Exception:
                self._native = None
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        if self._native is not None:
            return len(self._native)
        return len(self._record.keys)

    def __getitem__(self, idx):
        if self._native is not None:
            return self._native.read(idx)
        return self._record.read_idx(self._record.keys[idx])
