"""Gluon Trainer — params ↔ KVStore ↔ Optimizer bridge.

Reference surface: ``python/mxnet/gluon/trainer.py`` (SURVEY.md §3.2 "Gluon
Trainer"; §4.2 call stack): ``step(batch_size)`` = allreduce grads →
rescale → per-param optimizer update; split ``allreduce_grads()``/
``update()`` API for gradient clipping; ``update_on_kvstore`` runs the
update inside the store (the reference's optimizer-on-PS-server).

TPU-native: with a single chip or a GSPMD-sharded step the allreduce is
either identity or already inside the compiled step, so ``step`` reduces to
the fused optimizer update; the kvstore path is kept bit-compatible for
ported code.
"""
from __future__ import annotations

import pickle

import jax

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a (Parameter)Dict or list")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._compression_params = compression_params
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)
        self._optimizer_registered_on_kv = False

    def _init_optimizer(self, optimizer, optimizer_params):
        # kvstore keys are strings — register both forms so per-param
        # lr_mult/wd_mult hold in the update_on_kvstore path too
        param_dict = {i: p for i, p in enumerate(self._params)}
        param_dict.update({str(i): p for i, p in enumerate(self._params)})
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)

    # -- kvstore ----------------------------------------------------------- #
    def _init_kvstore(self):
        if self._kv_initialized:
            return
        if self._kv_type is None or self._kv_type == "":
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            if self._update_on_kvstore is None:
                # reference default: update on kvstore for dist, local
                # update otherwise (single-process TPU: local fused update)
                self._update_on_kvstore = str(self._kv_type).startswith(
                    "dist")
            needs_reduce = any(p._replicas is not None
                               for p in self._params)
            if (not self._update_on_kvstore
                    and not needs_reduce
                    and not hasattr(self._kv_type, "push")
                    and not str(self._kv_type).startswith("dist")):
                # a Parameter owns ONE canonical (possibly GSPMD-sharded)
                # array, so local pushpull would be an identity allreduce;
                # skip the store entirely (no weight mirror, no per-step
                # no-op) — jit/GSPMD handles cross-device reduction.
                # Params with per-ctx REPLICAS (multi-ctx initialize) do
                # need the store: pushpull sums the per-device grads.
                self._kvstore = None
            else:
                from .. import kvstore as kv_mod
                self._kvstore = self._kv_type \
                    if hasattr(self._kv_type, "push") else kv_mod.create(
                        self._kv_type if isinstance(self._kv_type, str)
                        else "device")
                if self._compression_params:
                    self._kvstore.set_gradient_compression(
                        self._compression_params)
                for i, p in enumerate(self._params):
                    if p.grad_req != "null":
                        self._kvstore.init(i, p.data())
                if self._update_on_kvstore:
                    self._kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- core step --------------------------------------------------------- #
    def _check_initialized(self):
        for p in self._params:
            if p._data is None and p._deferred_init is None:
                raise MXNetError(
                    f"parameter {p.name} is not initialized; call "
                    "initialize() and run a forward pass first")

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + rescale(1/batch_size) + update (reference
        ``Trainer.step``).

        Dispatches asynchronously end to end — with an int ``batch_size``
        (the ``data.shape[0]`` idiom) nothing here reads a device value
        back to host, so a training loop fed by the device-prefetch input
        pipeline (``DataLoader(device=...)``) keeps batch ``k+1``'s host
        decode + H2D copy overlapped with this step's device compute."""
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / float(batch_size)
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        """Explicit allreduce for the clip-then-update pattern."""
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() is not supported with update_on_kvstore")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not live:
            return
        if self._update_on_kvstore:
            # push ALL grads in one wave: the store's server-side
            # optimizer applies them as one fused multi_update (one
            # jitted call per group instead of one per parameter), then
            # pull the updated weights back
            keys = [i for i, _ in live]
            self._kvstore.push(keys, [p.list_grad() for _, p in live])
            self._kvstore.pull(keys, [p.list_data() for _, p in live])
        else:
            for i, p in live:
                self._kvstore.pushpull(i, p.list_grad(), out=p.list_grad())

    def update(self, batch_size, ignore_stale_grad=False):
        """Update-only half of step (after manual allreduce + clipping)."""
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError("update() is not supported with "
                             "update_on_kvstore")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            return  # the push already applied the optimizer server-side
        # one fused multi-tensor apply over all live params: O(#groups)
        # jitted dispatches per step instead of O(#params) — the
        # reference's multi_sgd_update/aggregation path (the legacy
        # per-param loop is reachable via MXNET_FUSED_OPTIMIZER=0)
        idxs, ws, gs, ss = [], [], [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            if p._data is None:
                if ignore_stale_grad:
                    continue
                raise MXNetError(f"parameter {p.name} not initialized")
            if not self._states_created[i]:
                self._states[i] = \
                    self._optimizer.create_state_multi_precision(i, p.data())
                self._states_created[i] = True
            idxs.append(i)
            ws.append(p.data())
            gs.append(p.grad())
            ss.append(self._states[i])
        if not idxs:
            return
        new_states = self._optimizer.multi_update(idxs, ws, gs, ss)
        for i, ns in zip(idxs, new_states):
            self._states[i] = ns
            # broadcast updated weights to the other replicas (the
            # reference's kvstore weight pull after the server update);
            # skipped entirely on the single-canonical-array path so the
            # steady-state step stays a pure async dispatch chain
            p = self._params[i]
            if p._replicas is not None:
                p._sync_replicas()

    # -- state checkpointing (SURVEY.md §5.4 d) --------------------------- #
    def save_states(self, fname):
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
            return
        payload = {
            "num_update": self._optimizer.num_update,
            "index_update_count": self._optimizer._index_update_count,
            "states": [jax.tree.map(lambda a: jax.device_get(a), s)
                       for s, created in zip(self._states,
                                             self._states_created)
                       ],
            "created": self._states_created,
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        self._optimizer.num_update = payload["num_update"]
        self._optimizer._index_update_count = payload["index_update_count"]
        self._states = payload["states"]
        self._states_created = payload["created"]
