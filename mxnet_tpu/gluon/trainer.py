"""Gluon Trainer — params ↔ KVStore ↔ Optimizer bridge.

Reference surface: ``python/mxnet/gluon/trainer.py`` (SURVEY.md §3.2 "Gluon
Trainer"; §4.2 call stack): ``step(batch_size)`` = allreduce grads →
rescale → per-param optimizer update; split ``allreduce_grads()``/
``update()`` API for gradient clipping; ``update_on_kvstore`` runs the
update inside the store (the reference's optimizer-on-PS-server).

TPU-native: with a single chip or a GSPMD-sharded step the allreduce is
either identity or already inside the compiled step, so ``step`` reduces to
the fused optimizer update; the kvstore path is kept bit-compatible for
ported code.
"""
from __future__ import annotations

import itertools
import pickle

import jax

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]

# memory-ledger identity for ``telemetry.memory.ACCOUNTANT`` entries
# (``train.params`` / ``train.opt_states`` / ``train.grad_accum``) —
# monotonic, so a freed trainer's key is never reused
_trainer_seq = itertools.count()


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, update_interval=1):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a (Parameter)Dict or list")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._compression_params = compression_params
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)
        self._optimizer_registered_on_kv = False
        # gradient accumulation: apply the optimizer (and replica sync)
        # every Nth step() / fused_step() call; grads of the window's
        # micro-batches accumulate (on device on the fused path, in the
        # grad_req='add' buffers on the legacy path)
        self._update_interval = int(update_interval)
        if self._update_interval < 1:
            raise MXNetError("update_interval must be >= 1")
        self._window_pos = 0   # micro-batches seen in the current window
        # True while FusedStep's phase-by-phase fallback drives step():
        # it accumulates 'write' grads itself, so the grad_req guard in
        # step() must not fire
        self._accum_managed = False
        # id(loss_fn) -> FusedStep, strong refs (so ids stay unique),
        # FIFO-capped — see fused_step()
        self._fused_steps = {}
        # one-shot memory-ledger registration (params + optimizer
        # states are fixed-size once training starts; re-walking them
        # per step would be pure overhead)
        self._mem_label = f"trainer{next(_trainer_seq)}"
        self._mem_accounted = False

    def _mem_key(self):
        return self._mem_label

    def _account_params(self):
        """Register this trainer's device-resident training state with
        the process-wide memory accountant: parameter arrays under
        ``train.params`` and optimizer states under
        ``train.opt_states`` (``device_bytes{subsystem,device}``
        gauges).  Called from ``FusedStep._build`` on the fused path
        and from ``_update`` on the imperative path; it becomes a
        no-op flag check once every parameter is materialized — while
        deferred-init params remain (``step(ignore_stale_grad=True)``
        before a branch's first forward), it keeps re-registering so
        late initializations aren't permanently missing from the
        ledger."""
        if self._mem_accounted:
            return
        self._mem_accounted = all(p._data is not None
                                  for p in self._params)
        from ..telemetry.memory import ACCOUNTANT

        ACCOUNTANT.set(
            "train.params", self._mem_label,
            [p._data._data for p in self._params
             if p._data is not None])
        states = [s for s, created in zip(self._states,
                                          self._states_created)
                  if created]
        if states:
            ACCOUNTANT.set("train.opt_states", self._mem_label, states)

    def release_accounting(self):
        """Retire this trainer's memory-ledger entries (params,
        optimizer states, every cached FusedStep's accumulator ring).
        Runs on garbage collection; call it explicitly when discarding
        a trainer mid-process so ``device_bytes{subsystem="train.*"}``
        and ``reconcile()`` don't carry the dead trainer's bytes.
        Uses the accountant's DEFERRED drop: this is reachable from
        ``__del__``, and a finalizer may run via GC inside a thread
        already holding the accountant lock — taking it here would
        self-deadlock."""
        from ..telemetry.memory import ACCOUNTANT

        ACCOUNTANT.drop_deferred("train.params", self._mem_label)
        ACCOUNTANT.drop_deferred("train.opt_states", self._mem_label)
        for fs in self._fused_steps.values():
            fs.release_accounting()
        self._mem_accounted = False

    def __del__(self):
        try:
            self.release_accounting()
        except Exception:   # interpreter teardown: imports may be gone
            pass

    def _init_optimizer(self, optimizer, optimizer_params):
        # kvstore keys are strings — register both forms so per-param
        # lr_mult/wd_mult hold in the update_on_kvstore path too
        param_dict = {i: p for i, p in enumerate(self._params)}
        param_dict.update({str(i): p for i, p in enumerate(self._params)})
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)

    # -- kvstore ----------------------------------------------------------- #
    def _init_kvstore(self):
        if self._kv_initialized:
            return
        if self._kv_type is None or self._kv_type == "":
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            if self._update_on_kvstore is None:
                # reference default: update on kvstore for dist, local
                # update otherwise (single-process TPU: local fused update)
                self._update_on_kvstore = str(self._kv_type).startswith(
                    "dist")
            needs_reduce = any(p._replicas is not None
                               for p in self._params)
            if (not self._update_on_kvstore
                    and not needs_reduce
                    and not hasattr(self._kv_type, "push")
                    and not str(self._kv_type).startswith("dist")):
                # a Parameter owns ONE canonical (possibly GSPMD-sharded)
                # array, so local pushpull would be an identity allreduce;
                # skip the store entirely (no weight mirror, no per-step
                # no-op) — jit/GSPMD handles cross-device reduction.
                # Params with per-ctx REPLICAS (multi-ctx initialize) do
                # need the store: pushpull sums the per-device grads.
                self._kvstore = None
            else:
                from .. import kvstore as kv_mod
                self._kvstore = self._kv_type \
                    if hasattr(self._kv_type, "push") else kv_mod.create(
                        self._kv_type if isinstance(self._kv_type, str)
                        else "device")
                if self._compression_params:
                    self._kvstore.set_gradient_compression(
                        self._compression_params)
                for i, p in enumerate(self._params):
                    if p.grad_req != "null":
                        self._kvstore.init(i, p.data())
                if self._update_on_kvstore:
                    self._kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- core step --------------------------------------------------------- #
    def _check_initialized(self):
        for p in self._params:
            if p._data is None and p._deferred_init is None:
                raise MXNetError(
                    f"parameter {p.name} is not initialized; call "
                    "initialize() and run a forward pass first")

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + rescale(1/batch_size) + update (reference
        ``Trainer.step``).

        Dispatches asynchronously end to end — with an int ``batch_size``
        (the ``data.shape[0]`` idiom) nothing here reads a device value
        back to host, so a training loop fed by the device-prefetch input
        pipeline (``DataLoader(device=...)``) keeps batch ``k+1``'s host
        decode + H2D copy overlapped with this step's device compute.

        With ``Trainer(update_interval=N)``, ``batch_size`` is the
        MICRO-batch size: the first N-1 calls of each window only count
        (grads keep accumulating — use ``grad_req='add'`` or
        ``fused_step``); the Nth call allreduces, rescales ONCE by the
        effective batch ``N * batch_size``, applies the optimizer, and
        resets the ``'add'`` accumulators for the next window."""
        self._init_kvstore()
        if self._update_interval > 1:
            self._window_pos += 1
            if self._window_pos == 1 and not self._accum_managed:
                # a 'write' grad buffer is OVERWRITTEN by each backward:
                # mid-window micro-batches would be silently discarded —
                # fail loudly at the window's first step() instead
                bad = [p.name for p in self._params
                       if p.grad_req == "write"]
                if bad:
                    raise MXNetError(
                        "Trainer(update_interval="
                        f"{self._update_interval}) with step() requires "
                        "grad_req='add' so micro-batch gradients "
                        "accumulate; these parameters have "
                        f"grad_req='write' (first: {bad[0]}) and each "
                        "backward would overwrite, not accumulate. Set "
                        "grad_req='add' (then zero_grad() is automatic "
                        "at the window boundary) or drive the window "
                        "with fused_step(), which accumulates on "
                        "device.")
            if self._window_pos < self._update_interval:
                return  # mid-window micro-batch: accumulate only
            self._window_pos = 0
            self._optimizer.rescale_grad = self._scale / float(
                batch_size * self._update_interval)
            self._allreduce_grads()
            self._update(ignore_stale_grad)
            for p in self._params:
                if p.grad_req == "add":
                    p.zero_grad()
            return
        self._optimizer.rescale_grad = self._scale / float(batch_size)
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _check_window_boundary(self, what):
        if self._update_interval > 1 and self._window_pos != 0:
            raise MXNetError(
                f"{what} called mid-accumulation window (micro-batch "
                f"{self._window_pos}/{self._update_interval} of "
                f"Trainer(update_interval={self._update_interval})): "
                "syncing partial gradients would corrupt the accumulated "
                "update; call it only at the window boundary (after the "
                "Nth backward), or let step()/fused_step() drive the "
                "window")

    def allreduce_grads(self):
        """Explicit allreduce for the clip-then-update pattern."""
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() is not supported with update_on_kvstore")
        self._check_window_boundary("allreduce_grads()")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not live:
            return
        if self._update_on_kvstore:
            # push ALL grads in one wave: the store's server-side
            # optimizer applies them as one fused multi_update (one
            # jitted call per group instead of one per parameter), then
            # pull the updated weights back
            keys = [i for i, _ in live]
            self._kvstore.push(keys, [p.list_grad() for _, p in live])
            self._kvstore.pull(keys, [p.list_data() for _, p in live])
        else:
            for i, p in live:
                self._kvstore.pushpull(i, p.list_grad(), out=p.list_grad())

    def update(self, batch_size, ignore_stale_grad=False):
        """Update-only half of step (after manual allreduce + clipping).

        With ``update_interval=N``, ``batch_size`` is the micro-batch
        size and the rescale is by the effective batch ``N * batch_size``
        — applied ONCE on the accumulated grads, not per micro-batch."""
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError("update() is not supported with "
                             "update_on_kvstore")
        self._check_window_boundary("update()")
        self._optimizer.rescale_grad = self._scale / float(
            batch_size * self._update_interval)
        self._update(ignore_stale_grad)

    def zero_grad(self):
        """Reset the gradient buffers of every managed parameter to zero
        — the ``grad_req='add'`` accumulator reset that previously had to
        be hand-rolled as a loop over ``collect_params().values()``."""
        for p in self._params:
            if p.grad_req != "null":
                p.zero_grad()

    def _ensure_state(self, i):
        """Create optimizer state for param ``i`` once (shared by the
        fused step compiler and the imperative update loop, so the two
        paths interoperate on the same state list)."""
        if not self._states_created[i]:
            self._states[i] = \
                self._optimizer.create_state_multi_precision(
                    i, self._params[i].data())
            self._states_created[i] = True

    def fused_step(self, loss_fn, *batch, batch_size=None,
                   data_sharding=None):
        """One-executable train step: forward + loss + backward + grad
        rescale + (GSPMD) replica reduction + optimizer apply compiled
        into a single donated-buffer XLA dispatch
        (``gluon/fused_step.py``) — the reference's whole-step CachedOp
        amalgamation.  ``loss_fn(*batch)`` returns the per-sample loss
        (or ``(loss, *extras)``); define it ONCE outside the loop.
        ``batch_size`` defaults to ``batch[0].shape[0]``.  With
        ``update_interval=N`` grads accumulate on device and the apply
        (with its 1/(N·batch) rescale) fires every Nth call.  Pass
        ``data_sharding`` (e.g. ``parallel.collectives.dp_sharding``) to
        lay batches over the data axis so GSPMD compiles the grad
        all-reduce into the step.  ``MXNET_FUSED_STEP=0`` or an
        unsupported config (kvstore reduction, replicas, sparse, SGLD)
        falls back to the phase-by-phase path with identical semantics.
        On the fused path the tape and ``param.grad()`` buffers are never
        touched — gradients live only inside the executable."""
        from .fused_step import FusedStep

        fs = self._fused_steps.get(id(loss_fn))
        if fs is None:
            if len(self._fused_steps) >= 16:
                # a fresh lambda per loop iteration would otherwise pin
                # one compiled step (executables + device accumulators)
                # per call forever — evict oldest and tell the user once
                evicted = self._fused_steps.pop(
                    next(iter(self._fused_steps)))
                evicted.release_accounting()
                if not getattr(self, "_fused_evict_warned", False):
                    import warnings
                    warnings.warn(
                        "fused_step: more than 16 distinct loss_fn "
                        "objects seen — define the loss_fn ONCE outside "
                        "the training loop, or every call retraces",
                        stacklevel=2)
                    self._fused_evict_warned = True
            fs = FusedStep(self, loss_fn, data_sharding=data_sharding)
            self._fused_steps[id(loss_fn)] = fs
        return fs(batch, batch_size)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            return  # the push already applied the optimizer server-side
        # one fused multi-tensor apply over all live params: O(#groups)
        # jitted dispatches per step instead of O(#params) — the
        # reference's multi_sgd_update/aggregation path (the legacy
        # per-param loop is reachable via MXNET_FUSED_OPTIMIZER=0)
        idxs, ws, gs, ss = [], [], [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            if p._data is None:
                if ignore_stale_grad:
                    continue
                raise MXNetError(f"parameter {p.name} not initialized")
            self._ensure_state(i)
            idxs.append(i)
            ws.append(p.data())
            gs.append(p.grad())
            ss.append(self._states[i])
        if not idxs:
            return
        self._account_params()
        new_states = self._optimizer.multi_update(idxs, ws, gs, ss)
        for i, ns in zip(idxs, new_states):
            self._states[i] = ns
            # broadcast updated weights to the other replicas (the
            # reference's kvstore weight pull after the server update);
            # skipped entirely on the single-canonical-array path so the
            # steady-state step stays a pure async dispatch chain
            p = self._params[i]
            if p._replicas is not None:
                p._sync_replicas()

    # -- state checkpointing (SURVEY.md §5.4 d) --------------------------- #
    def save_states(self, fname):
        # mid-window, the true optimizer input includes the partial
        # gradient accumulator (device ring / 'add' buffers) that this
        # pickle does NOT capture — same contract as allreduce_grads():
        # refuse loudly rather than save a state that cannot resume
        # (use mx.checkpoint for mid-window-capable saves)
        self._check_window_boundary("save_states()")
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
            return
        payload = {
            "num_update": self._optimizer.num_update,
            "index_update_count": self._optimizer._index_update_count,
            "states": [jax.tree.map(lambda a: jax.device_get(a), s)
                       for s, created in zip(self._states,
                                             self._states_created)
                       ],
            "created": self._states_created,
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        # loading states mid-window would desync the donated fused-step
        # accumulator ring (its partial grads belong to the OLD states)
        self._check_window_boundary("load_states()")
        self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        self._optimizer.num_update = payload["num_update"]
        self._optimizer._index_update_count = payload["index_update_count"]
        self._states = payload["states"]
        self._states_created = payload["created"]
        # a clean state swap resets the accumulation window: any cached
        # FusedStep's ring (and the legacy host accumulator) belongs to
        # the pre-load run and must not mix into the next apply
        self._window_pos = 0
        for fs in self._fused_steps.values():
            fs._accum = None
            fs._legacy_accum = None
