"""Basic neural-network layers.

Reference surface: ``python/mxnet/gluon/nn/basic_layers.py`` (SURVEY.md §3.2
"Gluon layers"): Dense, Dropout, BatchNorm, LayerNorm, InstanceNorm,
GroupNorm, Embedding, Flatten, activations, Sequential/HybridSequential,
Lambda/HybridLambda.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ..block import Block, HybridBlock, commit_aux
from ..parameter import Parameter

__all__ = [
    "Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
    "LayerNorm", "RMSNorm", "InstanceNorm", "GroupNorm", "Embedding",
    "Flatten",
    "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU", "ELU",
    "SELU", "GELU", "Swish", "SyncBatchNorm",
]


class Sequential(Block):
    """Stack of Blocks executed in order."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)) and len(x) == 1:
                x = x[0]
        return x

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self.prefix)
            for block in children[key]:
                net.register_child(block)
            return net
        return children[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; hybridizes into one fused XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def hybrid_forward(self, F, x, *args):
        return self.forward(x, *args)

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self.prefix)
            for block in children[key]:
                net.register_child(block)
            return net
        return children[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """y = act(x W^T + b) — reference anchor ``FullyConnected`` + Gluon
    ``Dense``.  The matmul is MXU-shaped: (batch, in) x (in, units)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation) if activation else None

    def infer_shape(self, x, *args):
        in_units = int(onp.prod(x.shape[1:])) if self._flatten \
            else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving stats (reference anchor
    ``BatchNorm``).  Moving stats are committed functionally via
    ``commit_aux`` so hybridized traces stay pure (SURVEY.md §7 hard-part
    1)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd
        from ...ops import nn as _nnops
        out, new_mm, new_mv, _bm, _bv = _nnops._BatchNormStats(
            x, gamma, beta, running_mean, running_var, eps=self._eps,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            axis=self._axis, training=autograd.is_training())
        if autograd.is_training() and not self._use_global_stats:
            commit_aux(self.running_mean, new_mm)
            commit_aux(self.running_var, new_mv)
        return out

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, eps={self._eps}, "
                f"momentum={self._momentum}, "
                f"in_channels={self.gamma.shape[0] if self.gamma.shape else None})")


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: gluon.contrib.nn.SyncBatchNorm via
    kvstore/nccl).  Under GSPMD data parallelism the batch statistics are
    computed over the *global* batch automatically when the reduction runs
    inside a sharded jit — so this is BatchNorm plus a documented guarantee,
    not a separate comm path."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)

    def __repr__(self):
        return f"LayerNorm(axis={self._axis}, eps={self._eps})"


class RMSNorm(HybridBlock):
    """Root-mean-square norm (Llama-family; TPU-native addition — the
    reference has no RMSNorm layer)."""

    def __init__(self, axis=-1, epsilon=1e-6, gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[self._axis],)

    def hybrid_forward(self, F, x, gamma):
        return F.RMSNorm(x, gamma, axis=self._axis, eps=self._eps)

    def __repr__(self):
        return f"RMSNorm(axis={self._axis}, eps={self._eps})"


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        ch = x.shape[1]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._eps)


class Embedding(HybridBlock):
    """Index -> row lookup (reference anchor ``Embedding``).  Sharded tables
    come from setting a NamedSharding on ``weight`` (SURVEY.md §3.3 sparse
    row)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap an arbitrary function as a Block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            self._func = lambda F_, *a: getattr(F_, function)(*a)
            self._name_repr = function
        else:
            self._func = lambda F_, *a: function(F_, *a)
            self._name_repr = function.__name__
        self._function = function

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,),
                init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def hybrid_forward(self, F, x):
        return F.Activation(
            x, act_type="gelu" if self._approx != "erf" else "erf_gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        if self._beta == 1.0:
            return F.Activation(x, act_type="swish")
        return x * F.sigmoid(self._beta * x)
