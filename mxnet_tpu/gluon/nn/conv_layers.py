"""Convolution and pooling layers.

Reference surface: ``python/mxnet/gluon/nn/conv_layers.py`` (SURVEY.md §3.2
"Gluon layers"): Conv1-3D(+Transpose), Max/Avg/GlobalPool, reflection pad.
Convs lower to one ``lax.conv_general_dilated`` each — the MXU hot path.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ..block import HybridBlock
from .basic_layers import Activation

__all__ = [
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
    "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
    "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
    "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D",
    "GlobalAvgPool3D", "ReflectionPad2D",
]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution", adj=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._stride = _tuple(strides, ndim)
        self._pad = _tuple(padding, ndim)
        self._dilate = _tuple(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self._op_name = op_name
        self._adj = adj
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups
                          if in_channels else 0) + kernel_size
            else:  # Deconvolution: (in, out//groups, *k)
                wshape = (in_channels, channels // groups) + kernel_size \
                    if in_channels else (0, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation) if activation else None

    def infer_shape(self, x, *args):
        c_axis = 1 if self._layout[1] == "C" else len(self._layout) - 1
        in_channels = x.shape[c_axis]
        self._in_channels = in_channels
        if self._op_name == "Convolution":
            self.weight.shape = (self._channels,
                                 in_channels // self._groups) + self._kernel
        else:
            self.weight.shape = (in_channels,
                                 self._channels // self._groups) + self._kernel

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._op_name == "Convolution":
            out = F.Convolution(x, weight, bias, kernel=self._kernel,
                                stride=self._stride, dilate=self._dilate,
                                pad=self._pad, num_filter=self._channels,
                                num_group=self._groups, no_bias=bias is None,
                                layout=self._layout)
        else:
            out = F.Deconvolution(x, weight, bias, kernel=self._kernel,
                                  stride=self._stride, dilate=self._dilate,
                                  pad=self._pad, adj=self._adj or (),
                                  num_filter=self._channels,
                                  num_group=self._groups,
                                  no_bias=bias is None, layout=self._layout)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return (f"{type(self).__name__}({self._in_channels or None} -> "
                f"{self._channels}, kernel_size={self._kernel}, "
                f"stride={self._stride}, padding={self._pad})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tuple(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tuple(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tuple(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        ndim = len(pool_size)
        self._kernel = pool_size
        self._stride = _tuple(strides if strides is not None else pool_size,
                              ndim)
        self._pad = _tuple(padding, ndim)
        self._global = global_pool
        self._pool_type = pool_type
        self._layout = layout
        self._convention = "full" if ceil_mode else "valid"
        self._count_include_pad = count_include_pad

    def hybrid_forward(self, F, x):
        kw = {}
        if self._count_include_pad is not None:
            kw["count_include_pad"] = self._count_include_pad
        return F.Pooling(x, kernel=self._kernel, stride=self._stride,
                         pad=self._pad, pool_type=self._pool_type,
                         global_pool=self._global, layout=self._layout,
                         pooling_convention=self._convention, **kw)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._stride}, padding={self._pad})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuple(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuple(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuple(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, False, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, False, True, "max", layout,
                         **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, False, True, "max", layout,
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, False, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, False, True, "avg", layout,
                         **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, False, True, "avg", layout,
                         **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        p = self._padding
        pw = tuple((p[2 * i], p[2 * i + 1]) for i in range(4))
        return F.pad(x, mode="reflect", pad_width=pw)
