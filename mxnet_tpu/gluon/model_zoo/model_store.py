"""Pretrained-weight store (reference
``gluon/model_zoo/model_store.py``).

This environment has no network egress, so ``pretrained=True`` resolves
against a local cache directory only (``$MXNET_HOME/models`` or
``~/.mxnet/models``) and raises a clear error when the file is absent.
"""
from __future__ import annotations

import os

from ...base import MXNetError


def get_model_file(name, root=None):
    root = os.path.expanduser(root or os.path.join(
        os.environ.get("MXNET_HOME", os.path.join("~", ".mxnet")), "models"))
    fname = os.path.join(root, f"{name}.params")
    if os.path.isfile(fname):
        return fname
    raise MXNetError(
        f"pretrained weights for {name!r} not found at {fname}; this "
        f"environment has no network egress — place the .params file there "
        f"manually, or use pretrained=False")


def load_pretrained(net, name, ctx=None, root=None):
    net.load_parameters(get_model_file(name, root), ctx=ctx)
    return net


def purge(root=None):
    root = os.path.expanduser(root or os.path.join("~", ".mxnet", "models"))
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
