"""Gluon utilities (reference ``python/mxnet/gluon/utils.py``):
``split_data``, ``split_and_load``, ``clip_global_norm``, ``download``
(gated: no network in this environment), ``check_sha1``.
"""
from __future__ import annotations

import hashlib
import os

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split ``data`` into ``num_slice`` slices along ``batch_axis``
    (reference ``split_data``; feeds per-device shards for data parallel)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}; set "
            f"even_split=False")
    step = size // num_slice
    if not even_split:
        slices = []
        for i in range(num_slice):
            lo = i * step + min(i, size % num_slice)
            hi = lo + step + (1 if i < size % num_slice else 0)
            slices.append(_take_axis(data, batch_axis, lo, hi))
        return slices
    return [_take_axis(data, batch_axis, i * step, (i + 1) * step)
            for i in range(num_slice)]


def _take_axis(data, axis, lo, hi):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(lo, hi)
    return data[tuple(idx)]


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch across a context list and load each slice
    (reference ``split_and_load``).  On a 1-element ctx list this is a
    single ``as_in_context``.

    Batches that arrive PRE-SHARDED along ``batch_axis`` over exactly
    these devices (the ``DataLoader(device=[...])`` /
    ``DevicePrefetchIter`` path — one ``device_put`` with a batch-axis
    ``NamedSharding``) are returned as each device's already-resident
    shard: no host slicing, no re-transfer, no sync."""
    if not isinstance(data, NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    shards = _presharded_views(data, ctx_list, batch_axis)
    if shards is not None:
        return shards
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def _presharded_views(data, ctx_list, batch_axis):
    """Device-local views of an already batch-sharded array, in ctx order;
    ``None`` when the layout doesn't match (caller falls back to the host
    slice + per-device load path)."""
    if getattr(data, "_sparse_kind", False):
        return None
    arr = data._data
    sharding = getattr(arr, "sharding", None)
    if sharding is None or not hasattr(arr, "addressable_shards"):
        return None
    n = len(ctx_list)
    try:
        if sharding.is_fully_replicated or data.shape[batch_axis] % n != 0:
            return None
        shards = list(arr.addressable_shards)
    except Exception:
        return None
    if len(shards) != n:
        return None
    want = list(data.shape)
    want[batch_axis] //= n
    by_dev = {s.device: s for s in shards}
    out = []
    for i, ctx in enumerate(ctx_list):
        try:
            dev = ctx.jax_device() if hasattr(ctx, "jax_device") else ctx
        except Exception:
            return None
        s = by_dev.get(dev)
        if s is None or s.data is None or list(s.data.shape) != want:
            return None
        start = s.index[batch_axis].start or 0
        if start != i * want[batch_axis]:
            return None  # shard order disagrees with ctx order
        out.append(type(data)(s.data, ctx if hasattr(ctx, "jax_device")
                              else None))
    return out


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays in place so the global L2 norm ≤ max_norm; returns the
    norm (reference ``clip_global_norm``)."""
    if not arrays:
        raise MXNetError("clip_global_norm: empty array list")
    total = None
    for a in arrays:
        sq = (a * a).sum()
        total = sq if total is None else total + sq
    norm = float(total.sqrt().asnumpy()) if hasattr(total, "sqrt") else \
        float(onp.sqrt(float(total.asnumpy())))
    if check_isfinite and not onp.isfinite(norm):
        raise MXNetError(f"global norm is not finite ({norm}); gradients "
                         f"diverged or contain nan")
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference ``gluon.utils.download``.  This environment has no network
    egress; the function resolves to a pre-populated local cache if present
    and otherwise raises with instructions."""
    fname = url.split("/")[-1] if path is None or os.path.isdir(path or ".") \
        else path
    if path and os.path.isdir(path):
        fname = os.path.join(path, fname)
    elif path:
        fname = path
    if os.path.isfile(fname) and not overwrite and \
            (sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        f"download({url!r}) is unavailable: this environment has no network "
        f"egress.  Place the file at {fname!r} manually.")


def shape_is_known(shape):
    if shape is None:
        return False
    return all(isinstance(d, int) and d > 0 for d in shape)
