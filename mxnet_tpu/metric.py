"""Streaming evaluation metrics.

Reference surface: ``python/mxnet/metric.py`` (SURVEY.md §3.2 "metric":
Accuracy/TopK/F1/MCC/Perplexity/MAE/MSE/RMSE/CrossEntropy/NLL/PearsonCorr/
Composite/Custom with the ``update(labels, preds)`` protocol).
"""
from __future__ import annotations

import math

import numpy as onp

from .base import MXNetError

__all__ = [
    "EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "Perplexity",
    "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
    "PearsonCorrelation", "Loss", "CompositeEvalMetric", "CustomMetric",
    "create", "np",
]

_REGISTRY: dict = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = metric.lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
               "negativeloglikelihood", "top_k_accuracy": "topkaccuracy",
               "pearsonr": "pearsoncorrelation"}
    name = aliases.get(name, name)
    if name not in _REGISTRY:
        raise MXNetError(f"unknown metric {metric}")
    return _REGISTRY[name](*args, **kwargs)


def _to_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return onp.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"

    def update_dict(self, label, pred):
        lab = list(label.values())
        prd = list(pred.values())
        self.update(lab, prd)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.shape != label.shape:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(onp.int64).ravel()
            label = label.astype(onp.int64).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(onp.int64)
            topk = onp.argsort(-pred, axis=-1)[..., :self.top_k]
            hit = (topk == label[..., None]).any(axis=-1)
            self.sum_metric += hit.sum()
            self.num_inst += hit.size


@register
class F1(EvalMetric):
    """Binary F1 (reference semantics: preds are class-1 probabilities or
    2-col score arrays; labels 0/1)."""

    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset(self):
        super().reset()
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0
        self._macro_sum = 0.0
        self._macro_n = 0

    @staticmethod
    def _f1(tp, fp, fn):
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        return 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype(onp.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1).ravel()
            else:
                pred = (pred.ravel() > 0.5).astype(onp.int64)
            tp = int(((pred == 1) & (label == 1)).sum())
            fp = int(((pred == 1) & (label == 0)).sum())
            fn = int(((pred == 0) & (label == 1)).sum())
            self.tp += tp
            self.fp += fp
            self.fn += fn
            # macro (reference default): average per-batch F1 scores
            self._macro_sum += self._f1(tp, fp, fn)
            self._macro_n += 1
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        if self.average == "micro":
            return self.name, self._f1(self.tp, self.fp, self.fn)
        return self.name, self._macro_sum / self._macro_n


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.reset_stats()

    def reset(self):
        super().reset()
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = self.tn = 0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype(onp.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1).ravel()
            else:
                pred = (pred.ravel() > 0.5).astype(onp.int64)
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.tn += int(((pred == 0) & (label == 0)).sum())
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        denom = math.sqrt((self.tp + self.fp) * (self.tp + self.fn) *
                          (self.tn + self.fp) * (self.tn + self.fn))
        mcc = (self.tp * self.tn - self.fp * self.fn) / denom if denom else 0.0
        return self.name, mcc


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(onp.int64)
            if self.axis not in (-1, pred.ndim - 1):
                pred = onp.moveaxis(pred, self.axis, -1)
            flat_pred = pred.reshape(-1, pred.shape[-1])
            flat_label = label.ravel()
            probs = flat_pred[onp.arange(len(flat_label)), flat_label]
            if self.ignore_label is not None:
                ignore = flat_label == self.ignore_label
                probs = onp.where(ignore, 1.0, probs)
                num = (~ignore).sum()
            else:
                num = len(flat_label)
            self.sum_metric += -onp.log(onp.maximum(probs, 1e-10)).sum()
            self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += onp.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += ((label - pred) ** 2).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name=name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.sqrt(self.sum_metric / self.num_inst)


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype(onp.int64)
            pred = _to_numpy(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[onp.arange(len(label)), label]
            self.sum_metric += (-onp.log(prob + self.eps)).sum()
            self.num_inst += len(label)


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._labels = []
        self._preds = []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_numpy(label).ravel())
            self._preds.append(_to_numpy(pred).ravel())
            self.num_inst += 1

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        x = onp.concatenate(self._labels)
        y = onp.concatenate(self._preds)
        r = onp.corrcoef(x, y)[0, 1]
        return self.name, float(r)


@register
class Loss(EvalMetric):
    """Mean of raw loss values (reference ``mx.metric.Loss``)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            pred = _to_numpy(pred)
            self.sum_metric += pred.sum()
            self.num_inst += pred.size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.append(name)
            values.append(value)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            result = self._feval(label, pred)
            if isinstance(result, tuple):
                s, n = result
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += result
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    """Wrap a numpy feval as a metric (reference ``mx.metric.np``)."""
    return CustomMetric(numpy_feval, name, allow_extra_outputs)
