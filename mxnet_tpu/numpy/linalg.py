"""``mx.np.linalg`` (reference ``python/mxnet/numpy/linalg.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from .multiarray import _run, ndarray, _coerce_arr

__all__ = ["norm", "svd", "cholesky", "qr", "inv", "pinv", "det", "slogdet",
           "solve", "lstsq", "eig", "eigh", "eigvals", "eigvalsh",
           "matrix_rank", "matrix_power", "multi_dot", "tensorinv",
           "tensorsolve",
           "LinAlgError", "cond", "cross", "diagonal", "matmul", "outer", "trace", "tensordot", "vecdot", "svdvals", "matrix_norm", "vector_norm", "matrix_transpose"]


def norm(x, ord=None, axis=None, keepdims=False):  # noqa: A002
    return _run("linalg_norm", lambda a: jnp.linalg.norm(
        a, ord=ord, axis=tuple(axis) if isinstance(axis, list) else axis,
        keepdims=keepdims), [x])


def svd(a, full_matrices=False, compute_uv=True):
    arr = _coerce_arr(a)
    r = jnp.linalg.svd(arr._data, full_matrices=full_matrices,
                       compute_uv=compute_uv)
    if compute_uv:
        return ndarray(r[0]), ndarray(r[1]), ndarray(r[2])
    return ndarray(r)


def cholesky(a):
    return _run("linalg_cholesky", jnp.linalg.cholesky, [a])


def qr(a, mode="reduced"):
    arr = _coerce_arr(a)
    q, r = jnp.linalg.qr(arr._data, mode=mode)
    return ndarray(q), ndarray(r)


def inv(a):
    return _run("linalg_inv", jnp.linalg.inv, [a])


def pinv(a, rcond=None):
    return _run("linalg_pinv", lambda x: jnp.linalg.pinv(x, rcond=rcond),
                [a])


def det(a):
    return _run("linalg_det", jnp.linalg.det, [a])


def slogdet(a):
    arr = _coerce_arr(a)
    sign, logdet = jnp.linalg.slogdet(arr._data)
    return ndarray(sign), ndarray(logdet)


def solve(a, b):
    return _run("linalg_solve", jnp.linalg.solve, [a, b])


def lstsq(a, b, rcond=None):
    arr, brr = _coerce_arr(a), _coerce_arr(b)
    x, res, rank, sv = jnp.linalg.lstsq(arr._data, brr._data, rcond=rcond)
    return ndarray(x), ndarray(res), int(rank), ndarray(sv)


def eig(a):
    arr = _coerce_arr(a)
    w, v = jnp.linalg.eig(arr._data)
    return ndarray(w), ndarray(v)


def eigh(a, UPLO="L"):
    arr = _coerce_arr(a)
    w, v = jnp.linalg.eigh(arr._data, UPLO=UPLO)
    return ndarray(w), ndarray(v)


def eigvals(a):
    return _run("linalg_eigvals", jnp.linalg.eigvals, [a])


def eigvalsh(a, UPLO="L"):
    return _run("linalg_eigvalsh",
                lambda x: jnp.linalg.eigvalsh(x, UPLO=UPLO), [a])


def matrix_rank(a, tol=None):
    return _run("linalg_matrix_rank",
                lambda x: jnp.linalg.matrix_rank(x, tol=tol), [a])


def matrix_power(a, n):
    return _run("linalg_matrix_power",
                lambda x: jnp.linalg.matrix_power(x, n), [a])


def multi_dot(arrays):
    return _run("linalg_multi_dot", lambda *xs: jnp.linalg.multi_dot(xs),
                list(arrays))


def tensorinv(a, ind=2):
    return _run("linalg_tensorinv",
                lambda x: jnp.linalg.tensorinv(x, ind=ind), [a])


def tensorsolve(a, b, axes=None):
    return _run("linalg_tensorsolve",
                lambda x, y: jnp.linalg.tensorsolve(x, y, axes=axes), [a, b])


# numpy-2.0 additions (array-API names)
class LinAlgError(Exception):
    """Reference numpy.linalg.LinAlgError surface."""


def cond(a, p=None):
    return _run("linalg_cond", lambda x: jnp.linalg.cond(x, p=p), [a])


def cross(a, b, axis=-1):
    return _run("linalg_cross",
                lambda x, y: jnp.linalg.cross(x, y, axis=axis), [a, b])


def diagonal(a, offset=0):
    return _run("linalg_diagonal",
                lambda x: jnp.linalg.diagonal(x, offset=offset), [a])


def matmul(a, b):
    return _run("linalg_matmul", jnp.matmul, [a, b])


def outer(a, b):
    return _run("linalg_outer", jnp.outer, [a, b])


def trace(a, offset=0, dtype=None):
    return _run("linalg_trace",
                lambda x: jnp.linalg.trace(x, offset=offset,
                                           dtype=dtype), [a])


def tensordot(a, b, axes=2):
    return _run("linalg_tensordot",
                lambda x, y: jnp.tensordot(x, y, axes=axes), [a, b])


def vecdot(a, b, axis=-1):
    return _run("linalg_vecdot",
                lambda x, y: jnp.linalg.vecdot(x, y, axis=axis), [a, b])


def svdvals(a):
    return _run("linalg_svdvals", jnp.linalg.svdvals, [a])


def matrix_norm(a, ord="fro", keepdims=False):
    return _run("linalg_matrix_norm",
                lambda x: jnp.linalg.matrix_norm(
                    x, ord=ord, keepdims=keepdims), [a])


def vector_norm(a, ord=2, axis=None, keepdims=False):
    return _run("linalg_vector_norm",
                lambda x: jnp.linalg.vector_norm(
                    x, ord=ord, axis=axis, keepdims=keepdims), [a])


def matrix_transpose(a):
    return _run("linalg_matrix_transpose", jnp.linalg.matrix_transpose,
                [a])
