"""``mx.np`` breadth extensions (round-3 corpus expansion).

The reference's ``mx.np`` namespace mirrors NumPy's public API
(SURVEY.md §3.2 "ndarray module": ``mx.np``/``mx.npx`` NumPy-compatible
namespace).  This module adds the functions the r2 surface was missing:

- NumPy-2.0 alias names (``acos``/``atan2``/``concat``/``permute_dims``/
  ``pow``/``bitwise_invert``...)
- jnp-backed structured functions (``cov``, ``vander``, ``select``,
  ``choose``, ``compress``, ``put_along_axis``, ``fill_diagonal`` (copy
  semantics), ``apply_along_axis``, ``unwrap``, ``trapezoid``,
  ``geomspace``, ``lexsort``, ``partition``/``argpartition``,
  ``divmod``/``modf``/``frexp``, ``heaviside``, ``histogram2d``,
  ``histogram_bin_edges``, index helpers)
- set operations (``isin``, ``intersect1d``, ``union1d``, ``setdiff1d``,
  ``setxor1d``, ``unique_*``) — result shapes are data-dependent, so
  these run on HOST numpy and return device arrays (imperative-only,
  like the reference's dynamic-shape ops; documented, not jittable)
- dtype/introspection passthroughs (``finfo``/``iinfo``/``issubdtype``/
  ``promote_types``/``broadcast_shapes``/``isscalar``/``iterable``).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

from .multiarray import (_coerce_arr, _into, _run, _run1, ndarray,
                         _make_unary, _make_binary)

__all__: list = []  # populated below


def _np_of(x):
    """Host numpy view of any array-ish input (for host-side set ops)."""
    a = _coerce_arr(x)
    return onp.asarray(a._data) if isinstance(a, ndarray) else onp.asarray(a)


def _dev(x):
    return ndarray(jnp.asarray(x))


def _export(name, fn):
    fn.__name__ = name
    globals()[name] = fn
    __all__.append(name)
    return fn


# --------------------------------------------------------------------------- #
# numpy-2.0 alias names over existing ufuncs
# --------------------------------------------------------------------------- #

_UNARY_ALIASES = {
    "acos": jnp.arccos, "acosh": jnp.arccosh, "asin": jnp.arcsin,
    "asinh": jnp.arcsinh, "atan": jnp.arctan, "atanh": jnp.arctanh,
    "bitwise_invert": jnp.invert, "bitwise_count": jnp.bitwise_count,
    "conjugate": jnp.conj, "spacing": jnp.spacing,
}
_BINARY_ALIASES = {
    "atan2": jnp.arctan2, "pow": jnp.power,
    "bitwise_left_shift": jnp.left_shift,
    "bitwise_right_shift": jnp.right_shift,
    "heaviside": jnp.heaviside,
}
for _n, _f in _UNARY_ALIASES.items():
    _export(_n, _make_unary(_n, _f))
for _n, _f in _BINARY_ALIASES.items():
    _export(_n, _make_binary(_n, _f))


# --------------------------------------------------------------------------- #
# jnp-backed structured functions
# --------------------------------------------------------------------------- #

def _structured(name, jfn, n_arr=1):
    def wrapper(*args, **kwargs):
        arrays, rest = list(args[:n_arr]), args[n_arr:]
        static = dict(kwargs)
        return _run(name, lambda *arrs: jfn(*arrs, *rest, **static), arrays)
    return _export(name, wrapper)


_structured("cov", jnp.cov)
_structured("vander", jnp.vander)
_structured("trapezoid", jnp.trapezoid)
_structured("unwrap", jnp.unwrap)
_structured("partition", jnp.partition)
_structured("argpartition", jnp.argpartition)
_structured("matrix_transpose", jnp.matrix_transpose)
_structured("permute_dims", jnp.permute_dims)
_structured("histogram_bin_edges", jnp.histogram_bin_edges)
_structured("poly", jnp.poly)
_structured("roots", jnp.roots)
_structured("polyadd", jnp.polyadd, n_arr=2)
_structured("polysub", jnp.polysub, n_arr=2)
_structured("polymul", jnp.polymul, n_arr=2)
_structured("polyder", jnp.polyder)
_structured("polyint", jnp.polyint)
_structured("vecdot", jnp.vecdot, n_arr=2)
_structured("sort_complex", jnp.sort_complex)
_structured("trim_zeros", jnp.trim_zeros)


def concat(arrays, axis=0):
    # np.concat takes a sequence first — coerce each element
    arrays = [_coerce_arr(a) for a in arrays]
    return _run("concat", lambda *arrs: jnp.concatenate(arrs, axis=axis),
                list(arrays))


_export("concat", concat)


def select(condlist, choicelist, default=0):
    conds = [_np_of(c).astype(bool) for c in condlist]
    return _run("select", lambda *arrs: jnp.select(
        [jnp.asarray(c) for c in conds], list(arrs), default),
        list(choicelist))


_export("select", select)


def choose(a, choices, mode="raise"):
    return _run("choose", lambda idx, *arrs: jnp.choose(
        idx.astype(jnp.int32), list(arrs),
        mode="clip" if mode == "raise" else mode),
        [a] + list(choices))


_export("choose", choose)


def compress(condition, a, axis=None):
    cond = _np_of(condition).astype(bool)          # host: dynamic shape
    data = _np_of(a)
    return _dev(onp.compress(cond, data, axis=axis))


_export("compress", compress)


def put_along_axis(arr, indices, values, axis):
    """Copy semantics (functional): returns the updated array."""
    def impl(a, idx, vals):
        return jnp.put_along_axis(a, idx.astype(jnp.int32), vals, axis,
                                  inplace=False)
    return _run("put_along_axis", impl, [arr, indices, values])


_export("put_along_axis", put_along_axis)


def fill_diagonal(a, val, wrap=False):
    """Copy semantics (functional): returns the filled array."""
    return _run1("fill_diagonal", lambda x: jnp.fill_diagonal(
        x, val, wrap=wrap, inplace=False), a)


_export("fill_diagonal", fill_diagonal)


def apply_along_axis(func1d, axis, arr, *args, **kwargs):
    return _run1("apply_along_axis", lambda x: jnp.apply_along_axis(
        func1d, axis, x, *args, **kwargs), arr)


_export("apply_along_axis", apply_along_axis)


def apply_over_axes(func, a, axes):
    return _run1("apply_over_axes",
                 lambda x: jnp.apply_over_axes(func, x, axes), a)


_export("apply_over_axes", apply_over_axes)


def lexsort(keys, axis=-1):
    keys = [_coerce_arr(k) for k in keys]
    return _run("lexsort", lambda *arrs: jnp.lexsort(arrs, axis=axis),
                list(keys))


_export("lexsort", lexsort)


def divmod(x1, x2):
    q = _run("floor_divide", jnp.floor_divide, [x1, x2])
    r = _run("remainder", jnp.remainder, [x1, x2])
    return q, r


_export("divmod", divmod)


def modf(x):
    frac = _run1("modf_frac", lambda a: jnp.modf(a)[0], x)
    whole = _run1("modf_whole", lambda a: jnp.modf(a)[1], x)
    return frac, whole


_export("modf", modf)


def frexp(x):
    m = _run1("frexp_m", lambda a: jnp.frexp(a)[0], x)
    e = _run1("frexp_e", lambda a: jnp.frexp(a)[1], x)
    return m, e


_export("frexp", frexp)


def histogram2d(x, y, bins=10, range=None, weights=None):
    h, ex, ey = onp.histogram2d(_np_of(x), _np_of(y), bins=bins,
                                range=range,
                                weights=None if weights is None
                                else _np_of(weights))
    return _dev(h), _dev(ex), _dev(ey)


_export("histogram2d", histogram2d)


def geomspace(start, stop, num=50, endpoint=True, dtype=None, axis=0):
    return _dev(jnp.geomspace(start, stop, num, endpoint=endpoint,
                              dtype=dtype, axis=axis))


_export("geomspace", geomspace)


def block(arrays):
    def conv(a):
        if isinstance(a, list):
            return [conv(x) for x in a]
        c = _coerce_arr(a)
        return c._data if isinstance(c, ndarray) else a
    return _dev(jnp.block(conv(arrays)))


_export("block", block)


def ix_(*args):
    return tuple(_dev(g) for g in onp.ix_(*[_np_of(a) for a in args]))


_export("ix_", ix_)


def tril_indices_from(arr, k=0):
    r, c = onp.tril_indices(_np_of(arr).shape[-2], k,
                            _np_of(arr).shape[-1])
    return _dev(r), _dev(c)


def triu_indices_from(arr, k=0):
    r, c = onp.triu_indices(_np_of(arr).shape[-2], k,
                            _np_of(arr).shape[-1])
    return _dev(r), _dev(c)


def mask_indices(n, mask_func, k=0):
    if mask_func == "tril":
        mask_func = onp.tril
    elif mask_func == "triu":
        mask_func = onp.triu
    r, c = onp.mask_indices(n, lambda m, kk: onp.asarray(
        mask_func(m, kk)), k)
    return _dev(r), _dev(c)


_export("tril_indices_from", tril_indices_from)
_export("triu_indices_from", triu_indices_from)
_export("mask_indices", mask_indices)


# --------------------------------------------------------------------------- #
# set operations — data-dependent result shapes: host numpy, device result
# --------------------------------------------------------------------------- #

def _setop(name, nfn, n_arr=2):
    def wrapper(*args, **kwargs):
        host = [_np_of(a) for a in args[:n_arr]]
        out = nfn(*host, *args[n_arr:], **kwargs)
        if isinstance(out, tuple):
            return tuple(_dev(o) for o in out)
        return _dev(out)
    return _export(name, wrapper)


_setop("isin", onp.isin)
_setop("in1d", onp.isin)  # modern alias of the deprecated in1d
_setop("intersect1d", onp.intersect1d)
_setop("union1d", onp.union1d)
_setop("setdiff1d", onp.setdiff1d)
_setop("setxor1d", onp.setxor1d)
_setop("unique_values", lambda a: onp.unique(a), n_arr=1)
_setop("unique_counts", lambda a: onp.unique(a, return_counts=True),
       n_arr=1)
_setop("unique_inverse", lambda a: onp.unique(a, return_inverse=True),
       n_arr=1)
_setop("unique_all", lambda a: onp.unique(
    a, return_index=True, return_inverse=True, return_counts=True),
    n_arr=1)


# --------------------------------------------------------------------------- #
# dtype / introspection passthroughs
# --------------------------------------------------------------------------- #

def _passthrough(name, fn):
    return _export(name, fn)


_passthrough("finfo", jnp.finfo)
_passthrough("iinfo", jnp.iinfo)
_passthrough("issubdtype", jnp.issubdtype)
_passthrough("promote_types", jnp.promote_types)
_passthrough("broadcast_shapes", jnp.broadcast_shapes)
_passthrough("isdtype", getattr(jnp, "isdtype", None) or (
    lambda dt, kind: onp.issubdtype(dt, kind)))
_passthrough("isscalar", onp.isscalar)
_passthrough("iterable", onp.iterable)
_passthrough("isrealobj", lambda x: onp.isrealobj(_np_of(x)))
_passthrough("iscomplexobj", lambda x: onp.iscomplexobj(_np_of(x)))


def isreal(x):
    return _run1("isreal", jnp.isreal, x)


def iscomplex(x):
    return _run1("iscomplex", jnp.iscomplex, x)


_export("isreal", isreal)
_export("iscomplex", iscomplex)


def astype(x, dtype, copy=True):
    return _run1("astype", lambda a: a.astype(jnp.dtype(dtype)), x)


def array_equiv(a1, a2):
    return bool(onp.array_equiv(_np_of(a1), _np_of(a2)))


_export("astype", astype)
_export("array_equiv", array_equiv)
