"""``mx.np`` package (reference ``python/mxnet/numpy/``)."""
from .multiarray import *  # noqa: F401,F403
from .multiarray import (ndarray, array, _coerce_arr, _run)  # noqa: F401
from .extensions import *  # noqa: F401,F403  (r3 breadth additions)
from . import linalg  # noqa: F401
from . import random  # noqa: F401
