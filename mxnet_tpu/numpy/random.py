"""``mx.np.random`` (reference ``python/mxnet/numpy/random.py``) — NumPy
random API over the framework's functional key stream (mx.random)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import random as _base
from ..ndarray.ndarray import NDArray
from ..ops.registry import Op, invoke
from .multiarray import ndarray, _coerce_arr

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint",
           "choice", "shuffle", "permutation", "gamma", "beta",
           "exponential", "poisson", "multinomial", "binomial",
           "lognormal", "laplace", "gumbel", "logistic", "chisquare",
           "standard_normal", "multivariate_normal", "pareto", "power",
           "rayleigh", "weibull", "geometric", "negative_binomial", "f"]

seed = _base.seed


def _sample(name, fn, extra=()):
    key = _base.next_key()
    o = Op(name=f"_npr_{name}", fn=fn, differentiable=False)
    out = invoke(o, [ndarray(key)] + [(_coerce_arr(e)) for e in extra], {})
    return out


def _shp(size):
    if size is None:
        return ()
    return (size,) if isinstance(size, int) else tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    return _sample("uniform", lambda k: jax.random.uniform(
        k, _shp(size), jnp.dtype(dtype or "float32"), low, high))


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    return _sample("normal", lambda k: jax.random.normal(
        k, _shp(size), jnp.dtype(dtype or "float32")) * scale + loc)


def standard_normal(size=None, dtype=None):
    return normal(0.0, 1.0, size, dtype)


def randn(*size):
    return normal(0.0, 1.0, size or None)


def rand(*size):
    return uniform(0.0, 1.0, size or None)


def randint(low, high=None, size=None, dtype=None):
    if high is None:
        low, high = 0, low
    return _sample("randint", lambda k: jax.random.randint(
        k, _shp(size), low, high, jnp.dtype(dtype or "int32")))


def choice(a, size=None, replace=True, p=None):
    def fn(k, *arrs):
        arr = arrs[0] if arrs else jnp.arange(a)
        prob = arrs[1] if len(arrs) > 1 else None
        return jax.random.choice(k, arr, _shp(size), replace, prob)
    extra = []
    if not isinstance(a, int):
        extra.append(a)
        if p is not None:
            extra.append(p)
    elif p is not None:
        extra = [jnp.arange(a), p]

        def fn(k, arr, prob):  # noqa: F811
            return jax.random.choice(k, arr, _shp(size), replace, prob)
    return _sample("choice", fn, extra)


def permutation(x):
    if isinstance(x, int):
        return _sample("permutation",
                       lambda k: jax.random.permutation(k, x))
    return _sample("permutation",
                   lambda k, a: jax.random.permutation(k, a), [x])


def shuffle(x):
    """In-place shuffle along axis 0 (reference semantics)."""
    r = permutation(x)
    x._rebind(r._data)
    return None


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None):
    return _sample("gamma", lambda k: jax.random.gamma(
        k, shape, _shp(size) if size is not None else None) * scale)


def beta(a, b, size=None, dtype=None, ctx=None):
    return _sample("beta", lambda k: jax.random.beta(
        k, a, b, _shp(size) if size is not None else None))


def exponential(scale=1.0, size=None):
    return _sample("exponential", lambda k: jax.random.exponential(
        k, _shp(size)) * scale)


def poisson(lam=1.0, size=None):
    return _sample("poisson", lambda k: jax.random.poisson(k, lam,
                                                           _shp(size)))


def multinomial(n, pvals, size=None):
    def fn(k, p):
        if size is None:
            return jax.random.multinomial(k, n, p)
        # output shape = batch dims (size) + event dim (len(pvals))
        return jax.random.multinomial(k, n, p,
                                      shape=_shp(size) + p.shape[-1:])
    return _sample("multinomial", fn, [pvals])


def binomial(n, p, size=None):
    return _sample("binomial", lambda k: jax.random.binomial(
        k, n, p, shape=_shp(size) if size is not None else None))


def lognormal(mean=0.0, sigma=1.0, size=None):
    return _sample("lognormal", lambda k: jnp.exp(
        jax.random.normal(k, _shp(size)) * sigma + mean))


def laplace(loc=0.0, scale=1.0, size=None):
    return _sample("laplace", lambda k: jax.random.laplace(
        k, _shp(size)) * scale + loc)


def gumbel(loc=0.0, scale=1.0, size=None):
    return _sample("gumbel", lambda k: jax.random.gumbel(
        k, _shp(size)) * scale + loc)


def logistic(loc=0.0, scale=1.0, size=None):
    return _sample("logistic", lambda k: jax.random.logistic(
        k, _shp(size)) * scale + loc)


def chisquare(df, size=None):
    return _sample("chisquare", lambda k: jax.random.chisquare(
        k, df, shape=_shp(size) if size is not None else None))


def multivariate_normal(mean, cov, size=None):
    def fn(k, m, c):
        return jax.random.multivariate_normal(
            k, m, c, shape=_shp(size) if size is not None else None)
    return _sample("multivariate_normal", fn, [mean, cov])


def pareto(a, size=None):
    return _sample("pareto", lambda k: jax.random.pareto(
        k, a, shape=_shp(size) if size is not None else None) - 1.0)


def power(a, size=None):
    """X = U^(1/a) (numpy power distribution)."""
    return _sample("power", lambda k: jax.random.uniform(
        k, _shp(size) if size is not None else ()) ** (1.0 / a))


def rayleigh(scale=1.0, size=None):
    return _sample("rayleigh", lambda k: scale * jnp.sqrt(
        -2.0 * jnp.log(jax.random.uniform(
            k, _shp(size) if size is not None else (),
            minval=jnp.finfo(jnp.float32).tiny))))


def weibull(a, size=None):
    return _sample("weibull", lambda k: jax.random.weibull_min(
        k, 1.0, a, shape=_shp(size) if size is not None else None))


def geometric(p, size=None):
    return _sample("geometric", lambda k: jax.random.geometric(
        k, p, shape=_shp(size) if size is not None else None))


def negative_binomial(n, p, size=None):
    """Gamma-Poisson mixture (numpy semantics)."""
    def fn(k):
        k1, k2 = jax.random.split(k)
        shp = _shp(size) if size is not None else ()
        lam = jax.random.gamma(k1, n, shape=shp) * (1.0 - p) / p
        return jax.random.poisson(k2, lam, shape=shp if size is not None
                                  else lam.shape)
    return _sample("negative_binomial", fn)


def f(dfnum, dfden, size=None):
    def fn(k):
        k1, k2 = jax.random.split(k)
        shp = _shp(size) if size is not None else ()
        num = jax.random.chisquare(k1, dfnum, shape=shp) / dfnum
        den = jax.random.chisquare(k2, dfden, shape=shp) / dfden
        return num / den
    return _sample("f", fn)
