"""``mx.np`` — NumPy-semantics array namespace.

Reference surface: ``python/mxnet/numpy/multiarray.py`` (SURVEY.md §3.2
"ndarray module": "mx.np/mx.npx NumPy-compatible namespace with ndarray
subclass, dispatch protocol").  The reference mirrors ~200 NumPy operators
as ``_np_*`` ops with NumPy broadcasting/dtype rules.

TPU-native: ``jax.numpy`` *is* a NumPy-semantics tensor library, so this
namespace is a thin autograd-recording bridge: each function unwraps
``ndarray`` inputs, runs the ``jnp`` function through the op-registry
``invoke`` (so the tape sees it and ``backward`` flows), and rewraps as
``mx.np.ndarray`` (class propagation via ``_wrap_like``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError, numeric_types
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from ..ops.registry import Op, invoke

newaxis = None
pi = onp.pi
e = onp.e
euler_gamma = onp.euler_gamma
inf = onp.inf
nan = onp.nan

# dtype aliases
float16 = onp.float16
float32 = onp.float32
float64 = onp.float64
bfloat16 = jnp.bfloat16
int8 = onp.int8
int16 = onp.int16
int32 = onp.int32
int64 = onp.int64
uint8 = onp.uint8
bool_ = onp.bool_
dtype = onp.dtype


class ndarray(NDArray):
    """NumPy-semantics array (reference ``mx.np.ndarray``).  Inherits the
    async-handle machinery from NDArray; operators and indexing already
    follow NumPy broadcasting in this framework."""

    def __repr__(self):
        try:
            return f"array({onp.asarray(self._data)!r:s})".replace(
                "array(array", "array(").rstrip(")") + ")"
        except Exception:
            return f"<np.ndarray tracer {self.shape}>"

    def as_nd_ndarray(self):
        out = NDArray(self._data, self._ctx)
        out._grad = self._grad
        out._grad_req = self._grad_req
        out._autograd_node = self._autograd_node
        out._autograd_idx = self._autograd_idx
        return out

    def as_np_ndarray(self):
        return self

    # NumPy semantics: comparisons return bool arrays (the nd namespace
    # returns float 0/1 like legacy MXNet)
    def __eq__(self, o):
        return _run("equal", jnp.equal, [self, o])

    def __ne__(self, o):
        return _run("not_equal", jnp.not_equal, [self, o])

    def __lt__(self, o):
        return _run("less", jnp.less, [self, o])

    def __le__(self, o):
        return _run("less_equal", jnp.less_equal, [self, o])

    def __gt__(self, o):
        return _run("greater", jnp.greater, [self, o])

    def __ge__(self, o):
        return _run("greater_equal", jnp.greater_equal, [self, o])

    def __hash__(self):
        return id(self)

    # numpy-style reductions/methods not on the base class
    def std(self, axis=None, ddof=0, keepdims=False):
        return std(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def var(self, axis=None, ddof=0, keepdims=False):
        return var(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def cumsum(self, axis=None):
        return cumsum(self, axis=axis)

    def copy(self):
        return ndarray(jnp.asarray(self._data), self._ctx)

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return reshape(self, shape)

    def flatten(self):
        return reshape(self, (-1,))

    def ravel(self):
        return reshape(self, (-1,))

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        return _run1("astype", lambda x: x.astype(jnp.dtype(dtype)), self)

    def mean(self, axis=None, keepdims=False):
        return mean(self, axis=axis, keepdims=keepdims)

    def sum(self, axis=None, keepdims=False):
        return sum(self, axis=axis, keepdims=keepdims)  # noqa: A001

    def dot(self, b):
        return dot(self, b)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return transpose(self, axes if axes else None)

    def squeeze(self, axis=None):
        return squeeze(self, axis)

    @property
    def T(self):
        return transpose(self, None)


# --------------------------------------------------------------------------- #
# bridge machinery
# --------------------------------------------------------------------------- #

def _coerce_arr(x):
    if isinstance(x, NDArray):
        return x
    if isinstance(x, (onp.ndarray, list, tuple)) or isinstance(
            x, numeric_types) or isinstance(x, (bool, onp.generic)):
        return ndarray(jnp.asarray(x))
    return x


def _run(name, fn, arrays, static=None):
    """invoke() with np-class outputs.  Every legacy NDArray arg is promoted
    to the np subclass first — invoke's ``_wrap_like`` keys the output class
    off the first NDArray arg, so a leading legacy array must not win."""
    arrays = [_coerce_arr(a) for a in arrays]
    arrays = [a.as_np_ndarray()
              if isinstance(a, NDArray) and not isinstance(a, ndarray) else a
              for a in arrays]
    return invoke(Op(name=f"_np_{name}", fn=fn), arrays, static or {})


def _run1(name, fn, a):
    return _run(name, fn, [a])


def _make_unary(name, jfn):
    def wrapper(x, out=None, **kwargs):
        r = _run(name, jfn, [x])
        return _into(out, r)
    wrapper.__name__ = name
    return wrapper


def _make_binary(name, jfn):
    def wrapper(x1, x2, out=None, **kwargs):
        r = _run(name, jfn, [x1, x2])
        return _into(out, r)
    wrapper.__name__ = name
    return wrapper


def _into(out, r):
    if out is not None:
        out._rebind(r._data, r._autograd_node, r._autograd_idx)
        return out
    return r


# --------------------------------------------------------------------------- #
# creation
# --------------------------------------------------------------------------- #

def array(object, dtype=None, ctx=None):  # noqa: A002
    if isinstance(object, NDArray):
        data = object._data
    else:
        data = object
        if dtype is None:
            try:
                if onp.asarray(object).dtype == onp.float64:
                    dtype = onp.float32
            except Exception:
                pass
    arr = jnp.asarray(data, dtype=dtype)
    if ctx is not None:
        arr = jax.device_put(arr, ctx.jax_device())
    return ndarray(arr, ctx)


def asarray(a, dtype=None):
    return a if isinstance(a, ndarray) and dtype is None else array(a, dtype)


def zeros(shape, dtype=float32, order="C", ctx=None):
    return array(jnp.zeros(_shp(shape), jnp.dtype(dtype or "float32")),
                 ctx=ctx)


def ones(shape, dtype=float32, order="C", ctx=None):
    return array(jnp.ones(_shp(shape), jnp.dtype(dtype or "float32")),
                 ctx=ctx)


def full(shape, fill_value, dtype=None, order="C", ctx=None):
    return array(jnp.full(_shp(shape), fill_value,
                          jnp.dtype(dtype) if dtype else None), ctx=ctx)


def empty(shape, dtype=float32, order="C", ctx=None):
    return zeros(shape, dtype, order, ctx)


def zeros_like(a, dtype=None):
    return _run1("zeros_like", lambda x: jnp.zeros_like(
        x, jnp.dtype(dtype) if dtype else None), a)


def ones_like(a, dtype=None):
    return _run1("ones_like", lambda x: jnp.ones_like(
        x, jnp.dtype(dtype) if dtype else None), a)


def full_like(a, fill_value, dtype=None):
    return _run1("full_like", lambda x: jnp.full_like(
        x, fill_value, jnp.dtype(dtype) if dtype else None), a)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return array(jnp.arange(start, stop, step,
                            jnp.dtype(dtype) if dtype else None), ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    r = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                     dtype=jnp.dtype(dtype) if dtype else None, axis=axis)
    if retstep:
        return array(r[0], ctx=ctx), float(r[1])
    return array(r, ctx=ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    return array(jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                              dtype=jnp.dtype(dtype) if dtype else None),
                 ctx=ctx)


def eye(N, M=None, k=0, dtype=float32, ctx=None):
    return array(jnp.eye(N, M, k, jnp.dtype(dtype or "float32")), ctx=ctx)


def identity(n, dtype=float32, ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def meshgrid(*xi, indexing="xy"):
    arrs = [x._data if isinstance(x, NDArray) else jnp.asarray(x) for x in xi]
    return [ndarray(r) for r in jnp.meshgrid(*arrs, indexing=indexing)]


def tril(m, k=0):
    return _run1("tril", lambda x: jnp.tril(x, k), m)


def triu(m, k=0):
    return _run1("triu", lambda x: jnp.triu(x, k), m)


def _shp(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


# --------------------------------------------------------------------------- #
# unary ufuncs
# --------------------------------------------------------------------------- #

_UNARY = {
    "negative": jnp.negative, "positive": jnp.positive, "abs": jnp.abs,
    "absolute": jnp.abs, "fabs": jnp.abs, "sign": jnp.sign,
    "exp": jnp.exp, "expm1": jnp.expm1, "exp2": jnp.exp2,
    "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt, "cbrt": jnp.cbrt, "square": jnp.square,
    "reciprocal": jnp.reciprocal,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "rint": jnp.rint, "fix": jnp.fix, "floor": jnp.floor,
    "ceil": jnp.ceil, "trunc": jnp.trunc, "round": jnp.round,
    "around": jnp.round,
    "logical_not": jnp.logical_not, "invert": jnp.invert,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
    "isposinf": jnp.isposinf, "isneginf": jnp.isneginf,
    "conj": jnp.conj, "real": jnp.real, "imag": jnp.imag,
    "angle": jnp.angle,
    "sinc": jnp.sinc, "i0": jnp.i0,
    "nan_to_num": jnp.nan_to_num,
}

_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "true_divide": jnp.true_divide,
    "floor_divide": jnp.floor_divide, "mod": jnp.mod,
    "remainder": jnp.remainder, "fmod": jnp.fmod,
    "power": jnp.power, "float_power": jnp.float_power,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin,
    "hypot": jnp.hypot, "arctan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp, "logaddexp2": jnp.logaddexp2,
    "copysign": jnp.copysign, "nextafter": jnp.nextafter,
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "less": jnp.less, "less_equal": jnp.less_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "left_shift": jnp.left_shift, "right_shift": jnp.right_shift,
    "gcd": jnp.gcd, "lcm": jnp.lcm,
    "ldexp": jnp.ldexp,
}

for _n, _f in _UNARY.items():
    globals()[_n] = _make_unary(_n, _f)
for _n, _f in _BINARY.items():
    globals()[_n] = _make_binary(_n, _f)


# --------------------------------------------------------------------------- #
# reductions
# --------------------------------------------------------------------------- #

def _axis_reduce(name, jfn):
    def wrapper(a, axis=None, dtype=None, out=None, keepdims=False, **kw):
        def impl(x):
            r = jfn(x, axis=_ax(axis), keepdims=keepdims, **kw)
            return r.astype(jnp.dtype(dtype)) if dtype else r
        return _into(out, _run(name, impl, [a]))
    wrapper.__name__ = name
    return wrapper


def _ax(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


sum = _axis_reduce("sum", jnp.sum)  # noqa: A001
prod = _axis_reduce("prod", jnp.prod)
mean = _axis_reduce("mean", jnp.mean)
nansum = _axis_reduce("nansum", jnp.nansum)
nanprod = _axis_reduce("nanprod", jnp.nanprod)
nanmean = _axis_reduce("nanmean", jnp.nanmean)


def _minmax(name, jfn):
    def wrapper(a, axis=None, out=None, keepdims=False):
        return _into(out, _run(name, lambda x: jfn(
            x, axis=_ax(axis), keepdims=keepdims), [a]))
    wrapper.__name__ = name
    return wrapper


max = _minmax("max", jnp.max)  # noqa: A001
min = _minmax("min", jnp.min)  # noqa: A001
amax = max
amin = min
nanmax = _minmax("nanmax", jnp.nanmax)
nanmin = _minmax("nanmin", jnp.nanmin)
ptp = _minmax("ptp", jnp.ptp)


def std(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
    return _into(out, _run("std", lambda x: jnp.std(
        x, axis=_ax(axis), ddof=ddof, keepdims=keepdims), [a]))


def var(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
    return _into(out, _run("var", lambda x: jnp.var(
        x, axis=_ax(axis), ddof=ddof, keepdims=keepdims), [a]))


def argmax(a, axis=None, out=None):
    return _into(out, _run("argmax", lambda x: jnp.argmax(x, axis=axis), [a]))


def argmin(a, axis=None, out=None):
    return _into(out, _run("argmin", lambda x: jnp.argmin(x, axis=axis), [a]))


def cumsum(a, axis=None, dtype=None, out=None):
    return _into(out, _run("cumsum", lambda x: jnp.cumsum(
        x, axis=axis, dtype=jnp.dtype(dtype) if dtype else None), [a]))


def cumprod(a, axis=None, dtype=None):
    return _run("cumprod", lambda x: jnp.cumprod(
        x, axis=axis, dtype=jnp.dtype(dtype) if dtype else None), [a])


def median(a, axis=None, out=None, keepdims=False):
    return _into(out, _run("median", lambda x: jnp.median(
        x, axis=_ax(axis), keepdims=keepdims), [a]))


def quantile(a, q, axis=None, keepdims=False):
    return _run("quantile", lambda x, qq: jnp.quantile(
        x, qq, axis=_ax(axis), keepdims=keepdims), [a, q])


def percentile(a, q, axis=None, keepdims=False):
    return _run("percentile", lambda x, qq: jnp.percentile(
        x, qq, axis=_ax(axis), keepdims=keepdims), [a, q])


def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        return mean(a, axis=axis)
    r = _run("average", lambda x, w: jnp.average(x, _ax(axis), w),
             [a, weights])
    if returned:
        sw = sum(asarray(weights), axis=axis)
        return r, sw
    return r


def all(a, axis=None, out=None, keepdims=False):  # noqa: A001
    return _into(out, _run("all", lambda x: jnp.all(
        x, axis=_ax(axis), keepdims=keepdims), [a]))


def any(a, axis=None, out=None, keepdims=False):  # noqa: A001
    return _into(out, _run("any", lambda x: jnp.any(
        x, axis=_ax(axis), keepdims=keepdims), [a]))


def count_nonzero(a, axis=None):
    return _run("count_nonzero",
                lambda x: jnp.count_nonzero(x, axis=_ax(axis)), [a])


# --------------------------------------------------------------------------- #
# manipulation
# --------------------------------------------------------------------------- #

def reshape(a, newshape, order="C"):
    return _run("reshape", lambda x: jnp.reshape(x, _shp(newshape)), [a])


def transpose(a, axes=None):
    return _run("transpose", lambda x: jnp.transpose(
        x, tuple(axes) if axes is not None else None), [a])


def swapaxes(a, axis1, axis2):
    return _run("swapaxes", lambda x: jnp.swapaxes(x, axis1, axis2), [a])


def moveaxis(a, source, destination):
    return _run("moveaxis", lambda x: jnp.moveaxis(x, source, destination),
                [a])


def rollaxis(a, axis, start=0):
    return _run("rollaxis", lambda x: jnp.rollaxis(x, axis, start), [a])


def expand_dims(a, axis):
    return _run("expand_dims", lambda x: jnp.expand_dims(x, axis), [a])


def squeeze(a, axis=None):
    return _run("squeeze", lambda x: jnp.squeeze(
        x, _ax(axis) if axis is not None else None), [a])


def ravel(a, order="C"):
    return reshape(a, (-1,))


def atleast_1d(*arys):
    rs = [_run("atleast_1d", jnp.atleast_1d, [a]) for a in arys]
    return rs[0] if len(rs) == 1 else rs


def atleast_2d(*arys):
    rs = [_run("atleast_2d", jnp.atleast_2d, [a]) for a in arys]
    return rs[0] if len(rs) == 1 else rs


def atleast_3d(*arys):
    rs = [_run("atleast_3d", jnp.atleast_3d, [a]) for a in arys]
    return rs[0] if len(rs) == 1 else rs


def broadcast_to(a, shape):
    return _run("broadcast_to", lambda x: jnp.broadcast_to(x, _shp(shape)),
                [a])


def broadcast_arrays(*args):
    arrs = [_coerce_arr(a) for a in args]
    datas = [a._data for a in arrs]
    return [ndarray(r) for r in jnp.broadcast_arrays(*datas)]


def concatenate(seq, axis=0, out=None):
    return _into(out, _run("concatenate",
                           lambda *xs: jnp.concatenate(xs, axis=axis),
                           list(seq)))


def stack(arrays, axis=0, out=None):
    return _into(out, _run("stack", lambda *xs: jnp.stack(xs, axis=axis),
                           list(arrays)))


def vstack(tup):
    return _run("vstack", lambda *xs: jnp.vstack(xs), list(tup))


def hstack(tup):
    return _run("hstack", lambda *xs: jnp.hstack(xs), list(tup))


def dstack(tup):
    return _run("dstack", lambda *xs: jnp.dstack(xs), list(tup))


def column_stack(tup):
    return _run("column_stack", lambda *xs: jnp.column_stack(xs), list(tup))


def split(ary, indices_or_sections, axis=0):
    sec = indices_or_sections
    if isinstance(sec, NDArray):
        sec = tuple(int(v) for v in sec.asnumpy())
    elif isinstance(sec, (list, tuple)):
        sec = tuple(int(v) for v in sec)
    r = _run("split", lambda x: tuple(jnp.split(x, sec, axis=axis)), [ary])
    return r if isinstance(r, list) else [r]


def array_split(ary, indices_or_sections, axis=0):
    sec = indices_or_sections
    r = _run("array_split",
             lambda x: tuple(jnp.array_split(x, sec, axis=axis)), [ary])
    return r if isinstance(r, list) else [r]


def hsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=1)


def vsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=0)


def tile(a, reps):
    return _run("tile", lambda x: jnp.tile(x, reps), [a])


def repeat(a, repeats, axis=None):
    return _run("repeat", lambda x: jnp.repeat(x, repeats, axis=axis), [a])


def roll(a, shift, axis=None):
    return _run("roll", lambda x: jnp.roll(x, shift, axis=axis), [a])


def flip(m, axis=None):
    return _run("flip", lambda x: jnp.flip(x, axis=axis), [m])


def fliplr(m):
    return flip(m, 1)


def flipud(m):
    return flip(m, 0)


def rot90(m, k=1, axes=(0, 1)):
    return _run("rot90", lambda x: jnp.rot90(x, k, axes), [m])


def pad(array, pad_width, mode="constant", **kwargs):  # noqa: A002
    return _run("pad", lambda x: jnp.pad(x, pad_width, mode=mode, **kwargs),
                [array])


def delete(arr, obj, axis=None):
    # concretize indices so jnp.delete handles duplicates/slices correctly
    if isinstance(obj, NDArray):
        obj = onp.asarray(obj.asnumpy())
    elif isinstance(obj, (list, tuple)):
        obj = onp.asarray(obj)
    return _run("delete", lambda x: jnp.delete(x, obj, axis=axis), [arr])


def insert(arr, obj, values, axis=None):
    return _run("insert", lambda x, v: jnp.insert(x, obj, v, axis=axis),
                [arr, values])


def append(arr, values, axis=None):
    return _run("append", lambda x, v: jnp.append(x, v, axis=axis),
                [arr, values])


def where(condition, x=None, y=None):
    if x is None and y is None:
        cond = _coerce_arr(condition)
        rs = jnp.where(cond._data)
        return tuple(ndarray(r) for r in rs)
    return _run("where", lambda c, a, b: jnp.where(c, a, b),
                [condition, x, y])


def clip(a, a_min, a_max, out=None):
    return _into(out, _run("clip", lambda x: jnp.clip(x, a_min, a_max), [a]))


def diag(v, k=0):
    return _run("diag", lambda x: jnp.diag(x, k), [v])


def diagonal(a, offset=0, axis1=0, axis2=1):
    return _run("diagonal",
                lambda x: jnp.diagonal(x, offset, axis1, axis2), [a])


def trace(a, offset=0, axis1=0, axis2=1):
    return _run("trace", lambda x: jnp.trace(x, offset, axis1, axis2), [a])


def tril_indices(n, k=0, m=None):
    r, c = jnp.tril_indices(n, k, m)
    return ndarray(r), ndarray(c)


def indices(dimensions, dtype=int32):
    return ndarray(jnp.indices(tuple(dimensions), jnp.dtype(dtype)))


def unravel_index(indices, shape):  # noqa: A002
    arr = _coerce_arr(indices)
    rs = jnp.unravel_index(arr._data, _shp(shape))
    return tuple(ndarray(r) for r in rs)


def ravel_multi_index(multi_index, dims, mode="raise"):
    arrs = [_coerce_arr(a)._data for a in multi_index]
    return ndarray(jnp.ravel_multi_index(tuple(arrs), _shp(dims), mode=mode))


def take(a, indices, axis=None, mode="clip"):  # noqa: A002
    return _run("take", lambda x, i: jnp.take(
        x, i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.floating)
        else i, axis=axis, mode=mode), [a, indices])


def take_along_axis(arr, indices, axis):  # noqa: A002
    return _run("take_along_axis",
                lambda x, i: jnp.take_along_axis(x, i, axis), [arr, indices])


def searchsorted(a, v, side="left"):
    return _run("searchsorted",
                lambda x, y: jnp.searchsorted(x, y, side=side), [a, v])


def sort(a, axis=-1, kind=None, order=None):
    return _run("sort", lambda x: jnp.sort(x, axis=axis), [a])


def argsort(a, axis=-1, kind=None, order=None):
    return _run("argsort", lambda x: jnp.argsort(x, axis=axis), [a])


def unique(ar, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    arr = _coerce_arr(ar)
    rs = jnp.unique(arr._data, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(rs, tuple):
        return tuple(ndarray(r) for r in rs)
    return ndarray(rs)


def nonzero(a):
    arr = _coerce_arr(a)
    return tuple(ndarray(r) for r in jnp.nonzero(arr._data))


def flatnonzero(a):
    arr = _coerce_arr(a)
    return ndarray(jnp.flatnonzero(arr._data))


def argwhere(a):
    arr = _coerce_arr(a)
    return ndarray(jnp.argwhere(arr._data))


def extract(condition, arr):
    c = _coerce_arr(condition)
    a = _coerce_arr(arr)
    return ndarray(jnp.extract(c._data, a._data))


def copy(a):
    return _run("copy", jnp.copy, [a])


def may_share_memory(a, b, max_work=None):
    return False  # functional arrays never alias user-visibly


def shares_memory(a, b, max_work=None):
    return False


# --------------------------------------------------------------------------- #
# linear algebra (top-level)
# --------------------------------------------------------------------------- #

def dot(a, b, out=None):
    return _into(out, _run("dot", jnp.dot, [a, b]))


def matmul(a, b, out=None):
    return _into(out, _run("matmul", jnp.matmul, [a, b]))


def inner(a, b):
    return _run("inner", jnp.inner, [a, b])


def outer(a, b):
    return _run("outer", jnp.outer, [a, b])


def tensordot(a, b, axes=2):
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                   for x in ax)
    return _run("tensordot", lambda x, y: jnp.tensordot(x, y, ax), [a, b])


def einsum(subscripts, *operands, out=None, optimize=False):
    return _into(out, _run("einsum",
                           lambda *xs: jnp.einsum(subscripts, *xs),
                           list(operands)))


def kron(a, b):
    return _run("kron", jnp.kron, [a, b])


def cross(a, b, axis=-1):
    return _run("cross", lambda x, y: jnp.cross(x, y, axis=axis), [a, b])


def vdot(a, b):
    return _run("vdot", jnp.vdot, [a, b])


def interp(x, xp, fp, left=None, right=None):
    return _run("interp", lambda a, b, c: jnp.interp(a, b, c, left, right),
                [x, xp, fp])


def diff(a, n=1, axis=-1):
    return _run("diff", lambda x: jnp.diff(x, n, axis=axis), [a])


def ediff1d(ary):
    return _run("ediff1d", jnp.ediff1d, [ary])


def gradient(f, *varargs, axis=None):
    arr = _coerce_arr(f)
    rs = jnp.gradient(arr._data, *varargs, axis=axis)
    if isinstance(rs, list):
        return [ndarray(r) for r in rs]
    return ndarray(rs)


def convolve(a, v, mode="full"):
    return _run("convolve", lambda x, y: jnp.convolve(x, y, mode), [a, v])


def correlate(a, v, mode="valid"):
    return _run("correlate", lambda x, y: jnp.correlate(x, y, mode), [a, v])


def histogram(a, bins=10, range=None, weights=None):  # noqa: A002
    arr = _coerce_arr(a)
    h, edges = jnp.histogram(arr._data, bins=bins, range=range,
                             weights=None if weights is None
                             else _coerce_arr(weights)._data)
    return ndarray(h), ndarray(edges)


def bincount(x, weights=None, minlength=0):
    arr = _coerce_arr(x)
    return ndarray(jnp.bincount(
        arr._data, None if weights is None else _coerce_arr(weights)._data,
        minlength=minlength))


def digitize(x, bins, right=False):
    return _run("digitize", lambda a, b: jnp.digitize(a, b, right=right),
                [x, bins])


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _run("isclose", lambda x, y: jnp.isclose(
        x, y, rtol=rtol, atol=atol, equal_nan=equal_nan), [a, b])


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return bool(isclose(a, b, rtol, atol, equal_nan).asnumpy().all())


def array_equal(a1, a2):
    x, y = _coerce_arr(a1), _coerce_arr(a2)
    if x.shape != y.shape:
        return False
    return bool(jnp.array_equal(x._data, y._data))


def result_type(*args):
    vals = [a._data if isinstance(a, NDArray) else a for a in args]
    return onp.dtype(jnp.result_type(*vals))


def can_cast(from_, to):
    return onp.can_cast(from_, to)


def shape(a):
    return _coerce_arr(a).shape


def ndim(a):
    return _coerce_arr(a).ndim


def size(a, axis=None):
    arr = _coerce_arr(a)
    return arr.size if axis is None else arr.shape[axis]


def expm1x(x):
    return expm1(x)  # noqa: F821


def deg2rad(x):
    return _run1("deg2rad", jnp.deg2rad, x)


def rad2deg(x):
    return _run1("rad2deg", jnp.rad2deg, x)


def signbit(x):
    return _run1("signbit", jnp.signbit, x)


def empty_like(prototype, dtype=None, order="C"):
    p = _coerce_arr(prototype)
    return ndarray(jnp.empty_like(p._data, dtype=dtype))


def diagflat(v, k=0):
    return _run1("diagflat", lambda x: jnp.diagflat(x, k), v)


def diag_indices(n, ndim=2):
    rs = jnp.diag_indices(n, ndim)
    return tuple(ndarray(r) for r in rs)


def triu_indices(n, k=0, m=None):
    r, c = jnp.triu_indices(n, k, m)
    return ndarray(r), ndarray(c)


def tri(N, M=None, k=0, dtype=float32):
    return ndarray(jnp.tri(N, M, k, dtype=jnp.dtype(dtype)))


def dsplit(ary, indices_or_sections):
    a = _coerce_arr(ary)
    return [ndarray(x) for x in jnp.dsplit(a._data, indices_or_sections)]


def row_stack(tup):
    return _run("row_stack", lambda *xs: jnp.vstack(xs), list(tup))


def nanargmax(a, axis=None):
    return _run("nanargmax", lambda x: jnp.nanargmax(x, axis=axis), [a])


def nanargmin(a, axis=None):
    return _run("nanargmin", lambda x: jnp.nanargmin(x, axis=axis), [a])


def nancumsum(a, axis=None, dtype=None):
    return _run("nancumsum",
                lambda x: jnp.nancumsum(x, axis=axis, dtype=dtype), [a])


def nancumprod(a, axis=None, dtype=None):
    return _run("nancumprod",
                lambda x: jnp.nancumprod(x, axis=axis, dtype=dtype), [a])


def nanstd(a, axis=None, ddof=0, keepdims=False):
    return _run("nanstd", lambda x: jnp.nanstd(x, axis=axis, ddof=ddof,
                                               keepdims=keepdims), [a])


def nanvar(a, axis=None, ddof=0, keepdims=False):
    return _run("nanvar", lambda x: jnp.nanvar(x, axis=axis, ddof=ddof,
                                               keepdims=keepdims), [a])


def nanpercentile(a, q, axis=None, keepdims=False):
    return _run("nanpercentile",
                lambda x: jnp.nanpercentile(x, q, axis=axis,
                                            keepdims=keepdims), [a])


def corrcoef(x, y=None, rowvar=True):
    arrs = [x] if y is None else [x, y]
    if y is None:
        return _run("corrcoef",
                    lambda a: jnp.corrcoef(a, rowvar=rowvar), arrs)
    return _run("corrcoef",
                lambda a, b: jnp.corrcoef(a, b, rowvar=rowvar), arrs)


def trapz(y, x=None, dx=1.0, axis=-1):
    # jnp.trapezoid in current jax; trapz removed upstream
    fn = getattr(jnp, "trapezoid", None) or getattr(jnp, "trapz")
    if x is None:
        return _run("trapz", lambda yy: fn(yy, dx=dx, axis=axis), [y])
    return _run("trapz", lambda yy, xx: fn(yy, x=xx, axis=axis), [y, x])


def put(a, ind, v, mode="clip"):
    """Out-of-place semantics on XLA: returns the updated array AND rebinds
    ``a``'s handle (mutable-looking surface, SURVEY.md §7 Arrays)."""
    arr = _coerce_arr(a)
    idx = _coerce_arr(ind)._data.astype(jnp.int32).reshape(-1)
    vals = jnp.broadcast_to(jnp.asarray(
        _coerce_arr(v)._data, arr._data.dtype).reshape(-1), idx.shape) \
        if onp.ndim(getattr(_coerce_arr(v), "_data", v)) <= 1 else \
        _coerce_arr(v)._data.reshape(-1)
    flat = arr._data.reshape(-1)
    if mode == "clip":
        idx = jnp.clip(idx, 0, flat.shape[0] - 1)
    elif mode == "wrap":
        idx = idx % flat.shape[0]
    new = flat.at[idx].set(vals).reshape(arr._data.shape)
    if isinstance(a, NDArray):
        a._rebind(new)
        return a
    return ndarray(new)


def resize(a, new_shape):
    arr = _coerce_arr(a)
    return ndarray(jnp.resize(arr._data, new_shape))


def bitwise_not(a):
    return _run("bitwise_not", jnp.bitwise_not, [a])


invert = bitwise_not


def polyval(p, x):
    return _run("polyval", jnp.polyval, [p, x])


def blackman(M, dtype=None):
    return ndarray(jnp.blackman(M).astype(jnp.dtype(dtype or "float32")))


def hamming(M, dtype=None):
    return ndarray(jnp.hamming(M).astype(jnp.dtype(dtype or "float32")))


def hanning(M, dtype=None):
    return ndarray(jnp.hanning(M).astype(jnp.dtype(dtype or "float32")))


def diag_indices_from(arr):
    a = _coerce_arr(arr)
    return tuple(ndarray(ix) for ix in jnp.diag_indices_from(a._data))


def share_memory(a, b):
    # jax arrays are immutable buffers; views never alias mutably
    return False


def may_share_memory(a, b):
    return False


# everything public defined in this module (functions, constants, dtypes)
__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_")
           and _n not in ("jax", "jnp", "onp", "functools", "NDArray",
                          "Op", "invoke", "Context", "current_context",
                          "MXNetError", "numeric_types")]
