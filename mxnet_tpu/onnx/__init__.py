"""``mx.onnx`` — ONNX export + import (reference ``python/mxnet/onnx/``
mx2onnx and ``contrib/onnx`` onnx2mx; SURVEY.md §3.2 "ONNX" row)."""
from .mx2onnx import export_model, get_converter_registry
from .onnx2mx import import_model
