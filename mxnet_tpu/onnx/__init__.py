"""``mx.onnx`` — ONNX export (reference ``python/mxnet/onnx/`` mx2onnx;
SURVEY.md §3.2 "ONNX" row)."""
from .mx2onnx import export_model, get_converter_registry
