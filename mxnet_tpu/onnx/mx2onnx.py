"""mx2onnx — export a Symbol graph + params to ONNX.

Reference surface: ``python/mxnet/onnx/mx2onnx`` (SURVEY.md §3.2 "ONNX":
"op-by-op converter registry").  Each registered converter maps ONE graph
node (op name + attrs) to one-or-more ONNX node descriptors.

The ``onnx`` package is not installed in this environment; the converter
registry and graph construction are fully functional, and serialization
picks the best available container:

- with ``onnx`` importable → a real ``ModelProto`` written to ``.onnx``
- otherwise → the same graph as deterministic JSON (``.onnx.json``),
  loadable by the companion importer and by the tests.
"""
from __future__ import annotations

import json

import numpy as onp

from ..base import MXNetError

_CONVERTERS = {}

_OPSET = 13


def register_converter(opname):
    def deco(fn):
        _CONVERTERS[opname] = fn
        return fn
    return deco


def get_converter_registry():
    return dict(_CONVERTERS)


def _node(op_type, inputs, outputs, name, **attrs):
    return {"op_type": op_type, "inputs": list(inputs),
            "outputs": list(outputs), "name": name, "attrs": attrs}


# --------------------------------------------------------------------- #
# converters: fn(node_name, input_names, output_name, attrs) -> [nodes]
# --------------------------------------------------------------------- #

@register_converter("FullyConnected")
def _conv_fc(name, ins, out, attrs):
    nodes = []
    data = ins[0]
    if attrs.get("flatten", True):
        nodes.append(_node("Flatten", [data], [f"{name}_flat"],
                           f"{name}_flatten", axis=1))
        data = f"{name}_flat"
    gemm_ins = [data, ins[1]] + (ins[2:3] if len(ins) > 2 else [])
    nodes.append(_node("Gemm", gemm_ins, [out], name, alpha=1.0, beta=1.0,
                       transA=0, transB=1))
    return nodes


@register_converter("Convolution")
def _conv_conv(name, ins, out, attrs):
    kernel = list(attrs.get("kernel", ()))
    return [_node("Conv", ins, [out], name,
                  kernel_shape=kernel,
                  strides=list(attrs.get("stride", ())) or [1] * len(kernel),
                  pads=list(attrs.get("pad", ())) * 2 or [0] * 2 * len(kernel),
                  dilations=list(attrs.get("dilate", ())) or [1] * len(kernel),
                  group=int(attrs.get("num_group", 1)))]


@register_converter("Activation")
def _conv_act(name, ins, out, attrs):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = attrs.get("act_type", "relu")
    if act not in table:
        raise MXNetError(f"onnx: unsupported activation {act}")
    return [_node(table[act], ins, [out], name)]


@register_converter("relu")
def _conv_relu(name, ins, out, attrs):
    return [_node("Relu", ins, [out], name)]


@register_converter("sigmoid")
def _conv_sigmoid(name, ins, out, attrs):
    return [_node("Sigmoid", ins, [out], name)]


@register_converter("tanh")
def _conv_tanh(name, ins, out, attrs):
    return [_node("Tanh", ins, [out], name)]


@register_converter("softmax")
def _conv_softmax(name, ins, out, attrs):
    return [_node("Softmax", ins, [out], name,
                  axis=int(attrs.get("axis", -1)))]


@register_converter("log_softmax")
def _conv_log_softmax(name, ins, out, attrs):
    return [_node("LogSoftmax", ins, [out], name,
                  axis=int(attrs.get("axis", -1)))]


@register_converter("_BatchNormStats")
def _conv_bn(name, ins, out, attrs):
    # inputs: data, gamma, beta, moving_mean, moving_var (inference form)
    return [_node("BatchNormalization", ins[:5], [out], name,
                  epsilon=float(attrs.get("eps", 1e-5)),
                  momentum=float(attrs.get("momentum", 0.9)))]


@register_converter("LayerNorm")
def _conv_ln(name, ins, out, attrs):
    return [_node("LayerNormalization", ins, [out], name,
                  axis=int(attrs.get("axis", -1)),
                  epsilon=float(attrs.get("eps", 1e-5)))]


@register_converter("Pooling")
def _conv_pool(name, ins, out, attrs):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        op_type = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [_node(op_type, ins, [out], name)]
    kernel = list(attrs.get("kernel", ()))
    op_type = "MaxPool" if ptype == "max" else "AveragePool"
    return [_node(op_type, ins, [out], name, kernel_shape=kernel,
                  strides=list(attrs.get("stride", ())) or [1] * len(kernel),
                  pads=list(attrs.get("pad", ())) * 2 or [0] * 2 * len(kernel))]


@register_converter("flatten")
def _conv_flatten(name, ins, out, attrs):
    return [_node("Flatten", ins, [out], name, axis=1)]


@register_converter("reshape")
def _conv_reshape(name, ins, out, attrs):
    return [_node("Reshape", ins + [f"{name}_shape"], [out], name,
                  _const={f"{name}_shape":
                          onp.asarray(attrs.get("shape", (-1,)),
                                      onp.int64)})]


@register_converter("transpose")
def _conv_transpose(name, ins, out, attrs):
    return [_node("Transpose", ins, [out], name,
                  perm=list(attrs.get("axes", ())))]


@register_converter("concat")
def _conv_concat(name, ins, out, attrs):
    return [_node("Concat", ins, [out], name,
                  axis=int(attrs.get("dim", 1)))]


@register_converter("Embedding")
def _conv_embedding(name, ins, out, attrs):
    # data, weight -> Gather(weight, data)
    return [_node("Gather", [ins[1], ins[0]], [out], name, axis=0)]


@register_converter("dot")
def _conv_dot(name, ins, out, attrs):
    return [_node("MatMul", ins, [out], name)]


@register_converter("matmul")
def _conv_matmul(name, ins, out, attrs):
    return [_node("MatMul", ins, [out], name)]


for _mx, _onnx in [("broadcast_add", "Add"), ("broadcast_sub", "Sub"),
                   ("broadcast_mul", "Mul"), ("broadcast_div", "Div"),
                   ("broadcast_maximum", "Max"), ("broadcast_minimum", "Min"),
                   ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
                   ("abs", "Abs"), ("negative", "Neg"), ("erf", "Erf"),
                   ("identity", "Identity"), ("BlockGrad", "Identity")]:
    def _make(onnx_name):
        def conv(name, ins, out, attrs):
            return [_node(onnx_name, ins, [out], name)]
        return conv
    register_converter(_mx)(_make(_onnx))


def _reduce_converter(onnx_name, axes_as_input):
    """sum/mean carry axis+keepdims; MXNet default keepdims=False differs
    from ONNX's keepdims=1, and opset 13 ReduceSum takes axes as an INPUT
    tensor while ReduceMean still uses the attr."""

    def conv(name, ins, out, attrs):
        axis = attrs.get("axis")
        if axis is not None and not isinstance(axis, (list, tuple)):
            axis = [axis]
        keepdims = 1 if attrs.get("keepdims") else 0
        if axes_as_input:
            if axis is None:
                return [_node(onnx_name, ins, [out], name,
                              keepdims=keepdims)]
            return [_node(onnx_name, ins + [f"{name}_axes"], [out], name,
                          keepdims=keepdims,
                          _const={f"{name}_axes":
                                  onp.asarray(axis, onp.int64)})]
        kw = {"keepdims": keepdims}
        if axis is not None:
            kw["axes"] = [int(a) for a in axis]
        return [_node(onnx_name, ins, [out], name, **kw)]

    return conv


register_converter("sum")(_reduce_converter("ReduceSum", axes_as_input=True))
register_converter("mean")(_reduce_converter("ReduceMean",
                                             axes_as_input=False))


# --------------------------------------------------------------------- #
# export driver
# --------------------------------------------------------------------- #

def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", verbose=False, **kwargs):
    """Export (Symbol or exported json path, params dict or .params path)
    to ONNX (reference ``mx.onnx.export_model``)."""
    from ..symbol.symbol import Symbol, _topo
    from ..model import load_params_file
    from ..symbol import load as sym_load
    from ..ndarray import NDArray

    if isinstance(sym, str):
        sym = sym_load(sym)
    if not isinstance(sym, Symbol):
        raise MXNetError("export_model: sym must be a Symbol or json path")
    if isinstance(params, str):
        arg, aux = load_params_file(params)
        params = {**arg, **aux}

    nodes_out = []
    initializers = {}
    inputs = []
    # graph entry naming: node -> output names
    entry_name = {}
    for node in _topo(sym._heads):
        if node.op is None:
            entry_name[id(node)] = [node.name]
            if node.name in params:
                v = params[node.name]
                initializers[node.name] = (
                    v.asnumpy() if isinstance(v, NDArray) else onp.asarray(v))
            else:
                shp = None
                if input_shapes:
                    shp = dict(input_shapes).get(node.name) \
                        if isinstance(input_shapes, (list, dict)) else None
                dt = "float32"
                if input_types:
                    dt = str(dict(input_types).get(node.name, "float32")) \
                        if isinstance(input_types, (list, dict)) \
                        else str(input_types)
                inputs.append({"name": node.name,
                               "shape": list(shp) if shp else None,
                               "dtype": onp.dtype(dt).name})
            continue
        conv = _CONVERTERS.get(node.op)
        if conv is None:
            raise MXNetError(
                f"onnx: no converter registered for op {node.op!r} "
                f"({sorted(_CONVERTERS)} available)")
        in_names = [entry_name[id(i)][idx] for i, idx in node.inputs]
        n_out = node.num_outputs or 1
        out_names = [node.name if n_out == 1 else f"{node.name}_out{i}"
                     for i in range(n_out)]
        entry_name[id(node)] = out_names
        produced = conv(node.name, in_names, out_names[0], node.attrs)
        for p in produced:
            consts = p["attrs"].pop("_const", None)
            if consts:
                initializers.update(consts)
            nodes_out.append(p)

    outputs = [entry_name[id(n)][i] for n, i in sym._heads]
    graph = {
        "ir_version": 8,
        "opset": _OPSET,
        "producer": "mxnet_tpu",
        "graph": {
            "nodes": nodes_out,
            "inputs": inputs,
            "outputs": [{"name": o} for o in outputs],
            "initializers": {k: {"shape": list(v.shape),
                                 "dtype": str(v.dtype),
                                 "data": v.reshape(-1).tolist()}
                             for k, v in initializers.items()},
        },
    }
    try:
        import onnx  # noqa: F401
        return _write_protobuf(graph, initializers, onnx_file_path)
    except ImportError:
        path = onnx_file_path if onnx_file_path.endswith(".json") \
            else onnx_file_path + ".json"
        with open(path, "w") as f:
            json.dump(graph, f)
        if verbose:
            print(f"onnx package unavailable; wrote JSON container {path}")
        return path


def _write_protobuf(graph, initializers, path):
    import onnx
    from onnx import helper, numpy_helper, TensorProto
    nodes = [helper.make_node(n["op_type"], n["inputs"], n["outputs"],
                              name=n["name"], **n["attrs"])
             for n in graph["graph"]["nodes"]]
    inits = [numpy_helper.from_array(v, name=k)
             for k, v in initializers.items()]
    from onnx import mapping
    dtype_enum = {onp.dtype(k).name: v
                  for k, v in mapping.NP_TYPE_TO_TENSOR_TYPE.items()} \
        if hasattr(mapping, "NP_TYPE_TO_TENSOR_TYPE") else {}

    def _enum(dt):
        return dtype_enum.get(onp.dtype(dt).name, TensorProto.FLOAT)

    ins = [helper.make_tensor_value_info(
        i["name"], _enum(i.get("dtype", "float32")), i["shape"])
        for i in graph["graph"]["inputs"]]
    outs = [helper.make_tensor_value_info(o["name"], TensorProto.FLOAT, None)
            for o in graph["graph"]["outputs"]]
    g = helper.make_graph(nodes, "mxnet_tpu", ins, outs, initializer=inits)
    model = helper.make_model(
        g, opset_imports=[helper.make_opsetid("", graph["opset"])])
    onnx.save(model, path)
    return path
