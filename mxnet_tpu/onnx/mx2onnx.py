"""mx2onnx — export a Symbol graph + params to ONNX.

Reference surface: ``python/mxnet/onnx/mx2onnx`` (SURVEY.md §3.2 "ONNX":
"op-by-op converter registry").  Each registered converter maps ONE graph
node (op name + attrs) to one-or-more ONNX node descriptors.

The ``onnx`` package is not installed in this environment; the converter
registry and graph construction are fully functional, and serialization
picks the best available container:

- with ``onnx`` importable → a real ``ModelProto`` written to ``.onnx``
- otherwise → the same graph as deterministic JSON (``.onnx.json``),
  loadable by the companion importer and by the tests.
"""
from __future__ import annotations

import json
import warnings

import numpy as onp

from ..base import MXNetError

_CONVERTERS = {}

_OPSET = 13


def register_converter(opname):
    def deco(fn):
        _CONVERTERS[opname] = fn
        return fn
    return deco


def get_converter_registry():
    return dict(_CONVERTERS)


def _node(op_type, inputs, outputs, name, **attrs):
    return {"op_type": op_type, "inputs": list(inputs),
            "outputs": list(outputs), "name": name, "attrs": attrs}


# --------------------------------------------------------------------- #
# converters: fn(node_name, input_names, output_name, attrs) -> [nodes]
# --------------------------------------------------------------------- #

@register_converter("FullyConnected")
def _conv_fc(name, ins, out, attrs):
    nodes = []
    data = ins[0]
    if attrs.get("flatten", True):
        nodes.append(_node("Flatten", [data], [f"{name}_flat"],
                           f"{name}_flatten", axis=1))
        data = f"{name}_flat"
    gemm_ins = [data, ins[1]] + (ins[2:3] if len(ins) > 2 else [])
    nodes.append(_node("Gemm", gemm_ins, [out], name, alpha=1.0, beta=1.0,
                       transA=0, transB=1))
    return nodes


@register_converter("Convolution")
def _conv_conv(name, ins, out, attrs):
    kernel = list(attrs.get("kernel", ()))
    return [_node("Conv", ins, [out], name,
                  kernel_shape=kernel,
                  strides=list(attrs.get("stride", ())) or [1] * len(kernel),
                  pads=list(attrs.get("pad", ())) * 2 or [0] * 2 * len(kernel),
                  dilations=list(attrs.get("dilate", ())) or [1] * len(kernel),
                  group=int(attrs.get("num_group", 1)))]


@register_converter("Activation")
def _conv_act(name, ins, out, attrs):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = attrs.get("act_type", "relu")
    if act == "erf_gelu":
        # exact-erf gelu: 0.5·x·(1 + erf(x/√2)) — ONNX has no Gelu until
        # opset 20
        x = ins[0]
        return [
            _node("Div", [x, f"{name}_sqrt2"], [f"{name}_xs"], f"{name}_d",
                  _const={f"{name}_sqrt2":
                          onp.asarray(2.0 ** 0.5, onp.float32)}),
            _node("Erf", [f"{name}_xs"], [f"{name}_erf"], f"{name}_e"),
            _node("Add", [f"{name}_erf", f"{name}_one"], [f"{name}_1p"],
                  f"{name}_a",
                  _const={f"{name}_one": onp.asarray(1.0, onp.float32)}),
            _node("Mul", [x, f"{name}_1p"], [f"{name}_x1p"], f"{name}_m"),
            _node("Mul", [f"{name}_x1p", f"{name}_half"], [out], name,
                  _const={f"{name}_half":
                          onp.asarray(0.5, onp.float32)}),
        ]
    if act == "gelu":
        # the runtime's Activation('gelu') is jax.nn.gelu's TANH
        # approximation (ops/nn.py) — export the matching decomposition:
        # 0.5·x·(1 + tanh(√(2/π)·(x + 0.044715·x³)))
        x = ins[0]
        return [
            _node("Mul", [x, x], [f"{name}_x2"], f"{name}_sq"),
            _node("Mul", [f"{name}_x2", x], [f"{name}_x3"], f"{name}_cu"),
            _node("Mul", [f"{name}_x3", f"{name}_c0"], [f"{name}_cx3"],
                  f"{name}_m0",
                  _const={f"{name}_c0":
                          onp.asarray(0.044715, onp.float32)}),
            _node("Add", [x, f"{name}_cx3"], [f"{name}_in"], f"{name}_a0"),
            _node("Mul", [f"{name}_in", f"{name}_c1"], [f"{name}_sc"],
                  f"{name}_m1",
                  _const={f"{name}_c1":
                          onp.asarray((2.0 / onp.pi) ** 0.5,
                                      onp.float32)}),
            _node("Tanh", [f"{name}_sc"], [f"{name}_t"], f"{name}_th"),
            _node("Add", [f"{name}_t", f"{name}_one"], [f"{name}_1p"],
                  f"{name}_a1",
                  _const={f"{name}_one": onp.asarray(1.0, onp.float32)}),
            _node("Mul", [x, f"{name}_1p"], [f"{name}_x1p"], f"{name}_m2"),
            _node("Mul", [f"{name}_x1p", f"{name}_half"], [out], name,
                  _const={f"{name}_half":
                          onp.asarray(0.5, onp.float32)}),
        ]
    if act not in table:
        raise MXNetError(f"onnx: unsupported activation {act}")
    return [_node(table[act], ins, [out], name)]


@register_converter("relu")
def _conv_relu(name, ins, out, attrs):
    return [_node("Relu", ins, [out], name)]


@register_converter("sigmoid")
def _conv_sigmoid(name, ins, out, attrs):
    return [_node("Sigmoid", ins, [out], name)]


@register_converter("tanh")
def _conv_tanh(name, ins, out, attrs):
    return [_node("Tanh", ins, [out], name)]


@register_converter("softmax")
def _conv_softmax(name, ins, out, attrs):
    return [_node("Softmax", ins, [out], name,
                  axis=int(attrs.get("axis", -1)))]


@register_converter("log_softmax")
def _conv_log_softmax(name, ins, out, attrs):
    return [_node("LogSoftmax", ins, [out], name,
                  axis=int(attrs.get("axis", -1)))]


@register_converter("_BatchNormStats")
def _conv_bn(name, ins, out, attrs):
    # inputs: data, gamma, beta, moving_mean, moving_var (inference form)
    return [_node("BatchNormalization", ins[:5], [out], name,
                  epsilon=float(attrs.get("eps", 1e-5)),
                  momentum=float(attrs.get("momentum", 0.9)))]


@register_converter("LayerNorm")
def _conv_ln(name, ins, out, attrs):
    return [_node("LayerNormalization", ins, [out], name,
                  axis=int(attrs.get("axis", -1)),
                  epsilon=float(attrs.get("eps", 1e-5)))]


@register_converter("Pooling")
def _conv_pool(name, ins, out, attrs):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        op_type = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [_node(op_type, ins, [out], name)]
    kernel = list(attrs.get("kernel", ()))
    op_type = "MaxPool" if ptype == "max" else "AveragePool"
    return [_node(op_type, ins, [out], name, kernel_shape=kernel,
                  strides=list(attrs.get("stride", ())) or [1] * len(kernel),
                  pads=list(attrs.get("pad", ())) * 2 or [0] * 2 * len(kernel))]


@register_converter("flatten")
def _conv_flatten(name, ins, out, attrs):
    return [_node("Flatten", ins, [out], name, axis=1)]


@register_converter("reshape")
def _conv_reshape(name, ins, out, attrs):
    return [_node("Reshape", ins + [f"{name}_shape"], [out], name,
                  _const={f"{name}_shape":
                          onp.asarray(attrs.get("shape", (-1,)),
                                      onp.int64)})]


@register_converter("transpose")
def _conv_transpose(name, ins, out, attrs):
    return [_node("Transpose", ins, [out], name,
                  perm=list(attrs.get("axes", ())))]


@register_converter("concat")
def _conv_concat(name, ins, out, attrs):
    return [_node("Concat", ins, [out], name,
                  axis=int(attrs.get("dim", 1)))]


@register_converter("Embedding")
def _conv_embedding(name, ins, out, attrs):
    # data, weight -> Gather(weight, data)
    return [_node("Gather", [ins[1], ins[0]], [out], name, axis=0)]


@register_converter("dot")
def _conv_dot(name, ins, out, attrs):
    a, b = ins
    nodes = []
    # MXNet dot carries transpose flags; ONNX MatMul does not (2-D only —
    # batched dot exports via the batch_dot/matmul path).  dot on rank>2
    # is tensordot, which MatMul does NOT express — refuse loudly rather
    # than exporting silently wrong batched semantics.
    in_shapes = attrs.get("_in_shapes")
    if not in_shapes:
        # Without shape info the plain no-transpose dot exports as MatMul
        # — identical semantics for the 2-D case, which is what a
        # shape-free graph's dot overwhelmingly is, and what the
        # reference exporter emits.  It is NOT identical for rank>2
        # operands (dot is tensordot over the last/first axes; ONNX
        # MatMul batches), so the assumption is surfaced as a warning
        # rather than made silently.  The transpose flags lower to a
        # rank-2 Transpose(perm=[1,0]) and would be structurally wrong
        # without rank proof, so those still demand shapes.
        if attrs.get("transpose_a") or attrs.get("transpose_b"):
            raise MXNetError(
                "onnx: dot with transpose_a/transpose_b needs "
                "input_shapes at export time to prove the operands are "
                "2-D (the flags lower to a rank-2 Transpose)")
        warnings.warn(
            f"onnx: exporting shape-free dot '{name}' as MatMul, which "
            "assumes 2-D operands; rank>2 dot is tensordot and would "
            "need input_shapes at export time to refuse correctly",
            stacklevel=2)
        return [_node("MatMul", [a, b], [out], name)]
    if any(len(s) != 2 for s in in_shapes[:2]):
        raise MXNetError(
            f"onnx: dot export supports 2-D operands only, got shapes "
            f"{in_shapes[:2]} (rank>2 dot is tensordot — restructure "
            "with batch_dot/matmul)")
    if attrs.get("transpose_a"):
        nodes.append(_node("Transpose", [a], [f"{name}_aT"], f"{name}_ta",
                           perm=[1, 0]))
        a = f"{name}_aT"
    if attrs.get("transpose_b"):
        nodes.append(_node("Transpose", [b], [f"{name}_bT"], f"{name}_tb",
                           perm=[1, 0]))
        b = f"{name}_bT"
    nodes.append(_node("MatMul", [a, b], [out], name))
    return nodes


@register_converter("matmul")
def _conv_matmul(name, ins, out, attrs):
    return [_node("MatMul", ins, [out], name)]


@register_converter("slice_axis")
def _conv_slice_axis(name, ins, out, attrs):
    axis = int(attrs.get("axis", 0))
    begin = int(attrs.get("begin", 0))
    end = attrs.get("end")
    end = onp.iinfo(onp.int64).max if end is None else int(end)
    return [_node("Slice",
                  ins + [f"{name}_starts", f"{name}_ends", f"{name}_axes"],
                  [out], name,
                  _const={f"{name}_starts": onp.asarray([begin], onp.int64),
                          f"{name}_ends": onp.asarray([end], onp.int64),
                          f"{name}_axes": onp.asarray([axis], onp.int64)})]


@register_converter("broadcast_to")
def _conv_broadcast_to(name, ins, out, attrs):
    shape = list(attrs.get("shape", ()))
    if any(int(d) == 0 for d in shape):
        # MXNet's '0 keeps the input dim' has no ONNX Expand equivalent —
        # resolve against the inferred input shape
        in_shp = (attrs.get("_in_shapes") or [None])[0]
        if in_shp is None or len(in_shp) != len(shape):
            raise MXNetError(
                "onnx: broadcast_to with 0-dims ('keep input dim') needs "
                "input_shapes at export time to resolve them")
        shape = [int(i) if int(d) == 0 else int(d)
                 for d, i in zip(shape, in_shp)]
    return [_node("Expand", ins + [f"{name}_shape"], [out], name,
                  _const={f"{name}_shape": onp.asarray(shape, onp.int64)})]


@register_converter("flash_attention")
def _conv_flash(name, ins, out, attrs):
    """Decompose the fused attention op into the canonical ONNX pattern:
    MatMul(q, kᵀ)·scale [+ bias] → Softmax → MatMul(·, v).  The fused
    kernel is a TPU-side optimization; exported models get the portable
    graph every runtime understands."""
    if attrs.get("causal"):
        raise MXNetError(
            "onnx: causal flash_attention export not supported yet — "
            "encoder (BERT-style) attention only")
    scale = attrs.get("scale")
    if scale is None:
        shp = (attrs.get("_in_shapes") or [None])[0]
        if not shp:
            raise MXNetError(
                "onnx: flash_attention export needs input_shapes (to "
                "derive scale = 1/sqrt(head_dim)) or an explicit scale")
        scale = 1.0 / (float(shp[-1]) ** 0.5)
    q, k, v = ins[:3]
    bias = ins[3] if len(ins) > 3 else None
    nodes = [
        _node("Transpose", [k], [f"{name}_kT"], f"{name}_kt",
              perm=[0, 1, 3, 2]),
        _node("MatMul", [q, f"{name}_kT"], [f"{name}_qk"], f"{name}_qkm"),
        _node("Mul", [f"{name}_qk", f"{name}_scale"], [f"{name}_s"],
              f"{name}_sc",
              _const={f"{name}_scale": onp.asarray(scale, onp.float32)}),
    ]
    scores = f"{name}_s"
    if bias is not None:
        nodes.append(_node("Add", [scores, bias], [f"{name}_sb"],
                           f"{name}_ab"))
        scores = f"{name}_sb"
    nodes += [
        _node("Softmax", [scores], [f"{name}_p"], f"{name}_sm", axis=-1),
        _node("MatMul", [f"{name}_p", v], [out], name),
    ]
    return nodes


for _mx, _onnx in [("broadcast_add", "Add"), ("broadcast_sub", "Sub"),
                   ("broadcast_mul", "Mul"), ("broadcast_div", "Div"),
                   ("broadcast_maximum", "Max"), ("broadcast_minimum", "Min"),
                   ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
                   ("abs", "Abs"), ("negative", "Neg"), ("erf", "Erf"),
                   ("identity", "Identity"), ("BlockGrad", "Identity")]:
    def _make(onnx_name):
        def conv(name, ins, out, attrs):
            return [_node(onnx_name, ins, [out], name)]
        return conv
    register_converter(_mx)(_make(_onnx))


def _reduce_converter(onnx_name, axes_as_input):
    """sum/mean carry axis+keepdims; MXNet default keepdims=False differs
    from ONNX's keepdims=1, and opset 13 ReduceSum takes axes as an INPUT
    tensor while ReduceMean still uses the attr."""

    def conv(name, ins, out, attrs):
        axis = attrs.get("axis")
        if axis is not None and not isinstance(axis, (list, tuple)):
            axis = [axis]
        keepdims = 1 if attrs.get("keepdims") else 0
        if axes_as_input:
            if axis is None:
                return [_node(onnx_name, ins, [out], name,
                              keepdims=keepdims)]
            return [_node(onnx_name, ins + [f"{name}_axes"], [out], name,
                          keepdims=keepdims,
                          _const={f"{name}_axes":
                                  onp.asarray(axis, onp.int64)})]
        kw = {"keepdims": keepdims}
        if axis is not None:
            kw["axes"] = [int(a) for a in axis]
        return [_node(onnx_name, ins, [out], name, **kw)]

    return conv


register_converter("sum")(_reduce_converter("ReduceSum", axes_as_input=True))
register_converter("mean")(_reduce_converter("ReduceMean",
                                             axes_as_input=False))


# --------------------------------------------------------------------- #
# export driver
# --------------------------------------------------------------------- #

def _infer_node_shapes(sym, params, input_shapes, input_types):
    """Per-node output shapes via one eval_shape over the graph (the
    InferShape pass) — lets shape-dependent converters (flash_attention's
    1/sqrt(head_dim)) emit static constants.  Returns {} when inputs are
    underspecified; converters then degrade with explicit errors."""
    import jax

    from ..symbol.symbol import _topo, _node_outputs_abstract

    try:
        ishp = dict(input_shapes) if input_shapes else {}
        ityp = dict(input_types) if isinstance(input_types, (list, dict)) \
            else {}
        feed = {}
        for node in _topo(sym._heads):
            if node.op is not None:
                continue
            if node.name in params:
                v = params[node.name]
                arr = v.asnumpy() if hasattr(v, "asnumpy") \
                    else onp.asarray(v)
                feed[node.name] = jax.ShapeDtypeStruct(
                    arr.shape, onp.float32 if arr.dtype == onp.float64
                    else arr.dtype)
            else:
                if isinstance(input_types, (list, dict)):
                    dt = onp.dtype(str(ityp.get(node.name, "float32")))
                else:
                    dt = onp.dtype(str(input_types) if input_types
                                   else "float32")
                feed[node.name] = jax.ShapeDtypeStruct(
                    tuple(ishp[node.name]), dt)
        shapes = {}

        def run(*arrays):
            f = dict(zip(list(feed), arrays))
            memo = {}
            for node in _topo(sym._heads):
                if node.op is None:
                    memo[id(node)] = [f[node.name]]
                else:
                    ins = [memo[id(i)][idx] for i, idx in node.inputs]
                    memo[id(node)] = _node_outputs_abstract(node, ins)
                shapes[id(node)] = [tuple(o.shape)
                                    for o in memo[id(node)]]
            return [memo[id(n)][i] for n, i in sym._heads]

        jax.eval_shape(run, *feed.values())
        return shapes, None
    except Exception as e:
        # degrade (shape-dependent converters raise with this cause
        # attached) rather than failing every export for underspecified
        # inputs or a host-path op in the graph
        return {}, f"{type(e).__name__}: {e}"


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", verbose=False, **kwargs):
    """Export (Symbol or exported json path, params dict or .params path)
    to ONNX (reference ``mx.onnx.export_model``)."""
    from ..symbol.symbol import Symbol, _topo
    from ..model import load_params_file
    from ..symbol import load as sym_load
    from ..ndarray import NDArray

    if isinstance(sym, str):
        sym = sym_load(sym)
    if not isinstance(sym, Symbol):
        raise MXNetError("export_model: sym must be a Symbol or json path")
    if isinstance(params, str):
        arg, aux = load_params_file(params)
        params = {**arg, **aux}

    node_shapes, shape_err = _infer_node_shapes(sym, params, input_shapes,
                                                input_types)
    nodes_out = []
    initializers = {}
    inputs = []
    # graph entry naming: node -> output names
    entry_name = {}
    for node in _topo(sym._heads):
        if node.op is None:
            entry_name[id(node)] = [node.name]
            if node.name in params:
                v = params[node.name]
                initializers[node.name] = (
                    v.asnumpy() if isinstance(v, NDArray) else onp.asarray(v))
            else:
                shp = None
                if input_shapes:
                    shp = dict(input_shapes).get(node.name) \
                        if isinstance(input_shapes, (list, dict)) else None
                dt = "float32"
                if input_types:
                    dt = str(dict(input_types).get(node.name, "float32")) \
                        if isinstance(input_types, (list, dict)) \
                        else str(input_types)
                inputs.append({"name": node.name,
                               "shape": list(shp) if shp else None,
                               "dtype": onp.dtype(dt).name})
            continue
        conv = _CONVERTERS.get(node.op)
        if conv is None:
            raise MXNetError(
                f"onnx: no converter registered for op {node.op!r} "
                f"({sorted(_CONVERTERS)} available)")
        in_names = [entry_name[id(i)][idx] for i, idx in node.inputs]
        n_out = node.num_outputs or 1
        out_names = [node.name if n_out == 1 else f"{node.name}_out{i}"
                     for i in range(n_out)]
        entry_name[id(node)] = out_names
        attrs = node.attrs
        if node_shapes:
            attrs = {**attrs,
                     "_in_shapes": [node_shapes[id(i)][idx]
                                    for i, idx in node.inputs]}
        try:
            produced = conv(node.name, in_names, out_names[0], attrs)
        except MXNetError as e:
            if shape_err and ("input_shapes" in str(e)
                              or "_in_shapes" in str(e)):
                raise MXNetError(
                    f"{e}  (note: the InferShape pass failed with: "
                    f"{shape_err})") from e
            raise
        for p in produced:
            consts = p["attrs"].pop("_const", None)
            if consts:
                initializers.update(consts)
            nodes_out.append(p)

    outputs = [entry_name[id(n)][i] for n, i in sym._heads]
    graph = {
        "ir_version": 8,
        "opset": _OPSET,
        "producer": "mxnet_tpu",
        "graph": {
            "nodes": nodes_out,
            "inputs": inputs,
            "outputs": [{"name": o} for o in outputs],
            "initializers": {k: {"shape": list(v.shape),
                                 "dtype": str(v.dtype),
                                 "data": v.reshape(-1).tolist()}
                             for k, v in initializers.items()},
        },
    }
    try:
        import onnx  # noqa: F401
        return _write_protobuf(graph, initializers, onnx_file_path)
    except ImportError:
        path = onnx_file_path if onnx_file_path.endswith(".json") \
            else onnx_file_path + ".json"
        with open(path, "w") as f:
            json.dump(graph, f)
        if verbose:
            print(f"onnx package unavailable; wrote JSON container {path}")
        return path


def _write_protobuf(graph, initializers, path):
    import onnx
    from onnx import helper, numpy_helper, TensorProto
    nodes = [helper.make_node(n["op_type"], n["inputs"], n["outputs"],
                              name=n["name"], **n["attrs"])
             for n in graph["graph"]["nodes"]]
    inits = [numpy_helper.from_array(v, name=k)
             for k, v in initializers.items()]
    from onnx import mapping
    dtype_enum = {onp.dtype(k).name: v
                  for k, v in mapping.NP_TYPE_TO_TENSOR_TYPE.items()} \
        if hasattr(mapping, "NP_TYPE_TO_TENSOR_TYPE") else {}

    def _enum(dt):
        return dtype_enum.get(onp.dtype(dt).name, TensorProto.FLOAT)

    ins = [helper.make_tensor_value_info(
        i["name"], _enum(i.get("dtype", "float32")), i["shape"])
        for i in graph["graph"]["inputs"]]
    outs = [helper.make_tensor_value_info(o["name"], TensorProto.FLOAT, None)
            for o in graph["graph"]["outputs"]]
    g = helper.make_graph(nodes, "mxnet_tpu", ins, outs, initializer=inits)
    model = helper.make_model(
        g, opset_imports=[helper.make_opsetid("", graph["opset"])])
    onnx.save(model, path)
    return path
