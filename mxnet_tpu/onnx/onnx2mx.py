"""onnx2mx — import an ONNX model as (Symbol, arg_params, aux_params).

Reference surface: ``python/mxnet/contrib/onnx`` ``import_model``
(SURVEY.md §3.2 "ONNX": exporter + importer pair; VERDICT r1 item 6).

Accepts either a real ``.onnx`` ModelProto (when the ``onnx`` package is
importable) or the deterministic JSON container written by
``mx2onnx.export_model`` in onnx-less environments — the graph schema is
identical, so the converter table below serves both.

    sym, arg_params, aux_params = onnx2mx.import_model("model.onnx.json")
    mod = mx.mod.Module(sym, ...)   # or gluon.SymbolBlock(sym, ...)
"""
from __future__ import annotations

import json

import numpy as onp

from ..base import MXNetError

_IMPORTERS = {}


def register_importer(op_type):
    def deco(fn):
        _IMPORTERS[op_type] = fn
        return fn
    return deco


# --------------------------------------------------------------------- #
# converters: fn(sym_mod, inputs(list[Symbol]), attrs, consts, name)
#             -> Symbol
# ``consts`` maps initializer name -> numpy value for attr-carrying
# inputs (Reshape shape, ReduceSum axes, ...).
# --------------------------------------------------------------------- #

@register_importer("Gemm")
def _imp_gemm(sym, ins, attrs, consts, name):
    w_shape = consts.get("__shape__", {}).get(ins[1].name)
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    a = sym.transpose(ins[0], name=f"{name}_tA") \
        if attrs.get("transA", 0) else ins[0]
    if attrs.get("transB", 0) and alpha == 1.0 and beta == 1.0 \
            and w_shape is not None:
        # FullyConnected fast path (needs the weight initializer's shape)
        return sym.FullyConnected(a, ins[1],
                                  ins[2] if len(ins) > 2 else None,
                                  num_hidden=int(w_shape[0]),
                                  no_bias=len(ins) <= 2, flatten=False,
                                  name=name)
    # general form: alpha * A @ op(B) + beta * C
    b = sym.transpose(ins[1], name=f"{name}_tB") \
        if attrs.get("transB", 0) else ins[1]
    out = sym.matmul(a, b, name=f"{name}_mm")
    if alpha != 1.0:
        out = out * alpha
    if len(ins) > 2:
        c = ins[2] if beta == 1.0 else ins[2] * beta
        out = sym.broadcast_add(out, c, name=name)
    return out


def _no_w(name):
    raise MXNetError(f"onnx import: Conv {name} needs a weight "
                     "initializer to size num_filter")


def _sym_pads(pads, k, name):
    """ONNX pads are (begin..., end...); the Convolution/Pooling ops take
    symmetric pads — raise on asymmetric instead of silently truncating."""
    begin, end = list(pads[:k]), list(pads[k:])
    if end and begin != end:
        raise MXNetError(
            f"onnx import: asymmetric pads {pads} on {name} unsupported "
            "(symmetric begin==end only)")
    return tuple(begin)


@register_importer("Conv")
def _imp_conv(sym, ins, attrs, consts, name):
    kernel = tuple(attrs.get("kernel_shape", ()))
    pads = attrs.get("pads", [0] * (2 * len(kernel)))
    w_shape = consts.get("__shape__", {}).get(ins[1].name)
    return sym.Convolution(
        ins[0], ins[1], ins[2] if len(ins) > 2 else None,
        kernel=kernel,
        stride=tuple(attrs.get("strides", (1,) * len(kernel))),
        dilate=tuple(attrs.get("dilations", (1,) * len(kernel))),
        pad=_sym_pads(pads, len(kernel), name),
        num_filter=int(w_shape[0]) if w_shape is not None else _no_w(name),
        num_group=int(attrs.get("group", 1)),
        no_bias=len(ins) <= 2, name=name)


@register_importer("BatchNormalization")
def _imp_bn(sym, ins, attrs, consts, name):
    # inference form: (x - mean) / sqrt(var + eps) * gamma + beta
    x, gamma, beta, mean, var = ins[:5]
    eps = float(attrs.get("epsilon", 1e-5))
    shaped = [sym.reshape(s, shape=(1, -1, 1, 1), name=f"{name}_r{i}")
              for i, s in enumerate((gamma, beta, mean, var))]
    g, b, m, v = shaped
    denom = sym.sqrt(v + eps, name=f"{name}_std")
    return sym.broadcast_add(
        sym.broadcast_mul(sym.broadcast_div(
            sym.broadcast_sub(x, m, name=f"{name}_c"), denom,
            name=f"{name}_n"), g, name=f"{name}_s"),
        b, name=name)


@register_importer("LayerNormalization")
def _imp_ln(sym, ins, attrs, consts, name):
    return sym.LayerNorm(ins[0], ins[1], ins[2] if len(ins) > 2 else None,
                         axis=int(attrs.get("axis", -1)),
                         eps=float(attrs.get("epsilon", 1e-5)), name=name)


@register_importer("MaxPool")
def _imp_maxpool(sym, ins, attrs, consts, name):
    kernel = tuple(attrs.get("kernel_shape", ()))
    pads = attrs.get("pads", [0] * (2 * len(kernel)))
    return sym.Pooling(ins[0], kernel=kernel, pool_type="max",
                       stride=tuple(attrs.get("strides",
                                              (1,) * len(kernel))),
                       pad=_sym_pads(pads, len(kernel), name), name=name)


@register_importer("AveragePool")
def _imp_avgpool(sym, ins, attrs, consts, name):
    kernel = tuple(attrs.get("kernel_shape", ()))
    pads = attrs.get("pads", [0] * (2 * len(kernel)))
    return sym.Pooling(ins[0], kernel=kernel, pool_type="avg",
                       stride=tuple(attrs.get("strides",
                                              (1,) * len(kernel))),
                       pad=_sym_pads(pads, len(kernel), name), name=name)


@register_importer("GlobalMaxPool")
def _imp_gmaxpool(sym, ins, attrs, consts, name):
    return sym.Pooling(ins[0], kernel=(1, 1), pool_type="max",
                       global_pool=True, name=name)


@register_importer("GlobalAveragePool")
def _imp_gavgpool(sym, ins, attrs, consts, name):
    return sym.Pooling(ins[0], kernel=(1, 1), pool_type="avg",
                       global_pool=True, name=name)


@register_importer("Flatten")
def _imp_flatten(sym, ins, attrs, consts, name):
    return sym.flatten(ins[0], name=name)


@register_importer("Reshape")
def _imp_reshape(sym, ins, attrs, consts, name):
    shape = consts.get(ins[1].name) if len(ins) > 1 else \
        attrs.get("shape")
    if shape is None:
        raise MXNetError(f"onnx import: Reshape {name} needs a constant "
                         "shape input")
    return sym.reshape(ins[0], shape=tuple(int(s) for s in
                                           onp.asarray(shape).reshape(-1)),
                       name=name)


@register_importer("Transpose")
def _imp_transpose(sym, ins, attrs, consts, name):
    return sym.transpose(ins[0], axes=tuple(attrs.get("perm", ())),
                         name=name)


@register_importer("Concat")
def _imp_concat(sym, ins, attrs, consts, name):
    return sym.concat(*ins, dim=int(attrs.get("axis", 1)), name=name)


@register_importer("Gather")
def _imp_gather(sym, ins, attrs, consts, name):
    if int(attrs.get("axis", 0)) != 0:
        raise MXNetError("onnx import: Gather axis != 0 unsupported")
    return sym.take(ins[0], ins[1], name=name)


@register_importer("MatMul")
def _imp_matmul(sym, ins, attrs, consts, name):
    return sym.matmul(ins[0], ins[1], name=name)


@register_importer("Softmax")
def _imp_softmax(sym, ins, attrs, consts, name):
    return sym.softmax(ins[0], axis=int(attrs.get("axis", -1)), name=name)


@register_importer("LogSoftmax")
def _imp_log_softmax(sym, ins, attrs, consts, name):
    return sym.log_softmax(ins[0], axis=int(attrs.get("axis", -1)),
                           name=name)


def _simple(mx_op):
    def conv(sym, ins, attrs, consts, name):
        return getattr(sym, mx_op)(*ins, name=name)
    return conv


for _onnx, _mx in [("Relu", "relu"), ("Sigmoid", "sigmoid"),
                   ("Tanh", "tanh"), ("Softplus", "softrelu"),
                   ("Softsign", "softsign"), ("Exp", "exp"),
                   ("Log", "log"), ("Sqrt", "sqrt"), ("Abs", "abs"),
                   ("Neg", "negative"), ("Erf", "erf"),
                   ("Identity", "identity"),
                   ("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                   ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
                   ("Max", "broadcast_maximum"),
                   ("Min", "broadcast_minimum")]:
    register_importer(_onnx)(_simple(_mx))


@register_importer("Expand")
def _imp_expand(sym, ins, attrs, consts, name):
    shape = consts.get(ins[1].name)
    if shape is None:
        raise MXNetError("onnx import: Expand needs a constant shape")
    # _onnx_expand implements ONNX's numpy-broadcast semantics (a 1 in
    # the shape keeps the input dim) — plain broadcast_to would reject
    # valid external models
    return sym._onnx_expand(
        ins[0], shape=tuple(int(d) for d in onp.asarray(shape).reshape(-1)),
        name=name)


@register_importer("Slice")
def _imp_slice(sym, ins, attrs, consts, name):
    starts = consts.get(ins[1].name) if len(ins) > 1 else attrs.get("starts")
    ends = consts.get(ins[2].name) if len(ins) > 2 else attrs.get("ends")
    if starts is None or ends is None:
        raise MXNetError(
            "onnx import: Slice needs constant starts/ends (computed "
            "slice bounds are not supported)")
    def _opt_input(idx, what):
        """Optional trailing input: '' means spec-legal omission."""
        if len(ins) <= idx or not getattr(ins[idx], "name", ""):
            return None, False
        val = consts.get(ins[idx].name)
        if val is None:
            raise MXNetError(
                f"onnx import: Slice needs constant {what} (computed "
                f"{what} are not supported)")
        return val, True

    axes, have_axes = _opt_input(3, "axes")
    if not have_axes:
        axes = attrs.get("axes",
                         list(range(len(onp.asarray(starts).reshape(-1)))))
    steps, have_steps = _opt_input(4, "steps")
    if not have_steps:
        steps = attrs.get("steps")
    if steps is not None and any(int(s) != 1
                                 for s in onp.asarray(steps).reshape(-1)):
        raise MXNetError(
            "onnx import: Slice with steps != 1 (strided/reversed) is "
            "not supported")
    out = ins[0]
    int64_max = onp.iinfo(onp.int64).max
    for ax, b, e in zip(onp.asarray(axes).reshape(-1),
                        onp.asarray(starts).reshape(-1),
                        onp.asarray(ends).reshape(-1)):
        out = sym.slice_axis(out, axis=int(ax), begin=int(b),
                             end=None if int(e) >= int64_max else int(e))
    return out


@register_importer("ReduceSum")
def _imp_reduce_sum(sym, ins, attrs, consts, name):
    axes = consts.get(ins[1].name) if len(ins) > 1 else attrs.get("axes")
    kw = {"keepdims": bool(attrs.get("keepdims", 1))}
    if axes is not None:
        kw["axis"] = tuple(int(a) for a in onp.asarray(axes).reshape(-1))
    return sym.sum(ins[0], name=name, **kw)


@register_importer("ReduceMean")
def _imp_reduce_mean(sym, ins, attrs, consts, name):
    # axes: attr (≤ opset 17) or second constant input (opset 18+)
    axes = consts.get(ins[1].name) if len(ins) > 1 else attrs.get("axes")
    kw = {"keepdims": bool(attrs.get("keepdims", 1))}
    if axes is not None:
        kw["axis"] = tuple(int(a) for a in onp.asarray(axes).reshape(-1))
    return sym.mean(ins[0], name=name, **kw)


# --------------------------------------------------------------------- #
# import driver
# --------------------------------------------------------------------- #

def _load_container(model_file):
    """Normalize .onnx / .onnx.json into the JSON-container schema."""
    if str(model_file).endswith(".json"):
        with open(model_file) as f:
            return json.load(f)
    try:
        import onnx
        from onnx import numpy_helper
    except ImportError as e:
        raise MXNetError(
            "onnx package unavailable; import the JSON container "
            "(.onnx.json) written by export_model instead") from e
    model = onnx.load(model_file)
    g = model.graph
    inits = {i.name: numpy_helper.to_array(i) for i in g.initializer}
    return {
        "opset": (model.opset_import[0].version
                  if model.opset_import else 13),
        "graph": {
            "nodes": [{
                "op_type": n.op_type,
                "inputs": list(n.input),
                "outputs": list(n.output),
                "name": n.name or n.output[0],
                "attrs": {a.name: onnx.helper.get_attribute_value(a)
                          for a in n.attribute},
            } for n in g.node],
            "inputs": [{"name": i.name} for i in g.input
                       if i.name not in inits],
            "outputs": [{"name": o.name} for o in g.output],
            "initializers": {k: {"shape": list(v.shape),
                                 "dtype": str(v.dtype),
                                 "data": v.reshape(-1).tolist()}
                             for k, v in inits.items()},
        },
    }


def import_model(model_file):
    """Returns ``(sym, arg_params, aux_params)`` (reference
    ``mx.contrib.onnx.import_model`` signature)."""
    from .. import symbol as sym_mod
    from ..ndarray.ndarray import array

    container = _load_container(model_file)
    g = container["graph"]

    consts = {}
    shapes = {}
    params = {}
    for nm, spec in g["initializers"].items():
        v = onp.asarray(spec["data"], dtype=spec["dtype"]).reshape(
            spec["shape"])
        consts[nm] = v
        shapes[nm] = tuple(spec["shape"])
        params[nm] = array(v)
    consts["__shape__"] = shapes

    env = {}
    for i in g["inputs"]:
        env[i["name"]] = sym_mod.var(i["name"])
    for nm in g["initializers"]:
        env[nm] = sym_mod.var(nm)

    for node in g["nodes"]:
        imp = _IMPORTERS.get(node["op_type"])
        if imp is None:
            raise MXNetError(
                f"onnx import: no importer for {node['op_type']!r} "
                f"(have {sorted(_IMPORTERS)})")
        ins = []
        for nm in node["inputs"]:
            if nm not in env:
                # constant-only input (e.g. Reshape shape): keep the name
                # resolvable for consts[] lookups via a stub symbol
                env[nm] = sym_mod.var(nm)
            ins.append(env[nm])
        out_sym = imp(sym_mod, ins, node["attrs"], consts, node["name"])
        outs = out_sym if isinstance(out_sym, (list, tuple)) else [out_sym]
        for o_name, o_sym in zip(node["outputs"], outs):
            env[o_name] = o_sym

    heads = [env[o["name"]] for o in g["outputs"]]
    sym = heads[0] if len(heads) == 1 else sym_mod.Group(heads)
    # attr-only constants (Reshape shapes / ReduceSum axes) are consumed at
    # conversion time and must NOT surface as runtime arg_params
    used = set()
    for node in _collect_var_names(sym):
        used.add(node)
    arg_params = {k: v for k, v in params.items() if k in used}
    aux_params = {}
    return sym, arg_params, aux_params


def _collect_var_names(sym):
    from ..symbol.symbol import _topo
    names = []
    for node in _topo(sym._heads):
        if node.op is None:
            names.append(node.name)
    return names
