"""``mx.npx`` — NumPy-extension namespace (reference
``python/mxnet/numpy_extension/`` + ``mx.npx`` op surface): neural-network
ops that have no NumPy equivalent, plus the ``set_np``/``reset_np``
semantics switches."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from ..ops.registry import Op, invoke
from ..numpy.multiarray import ndarray, _coerce_arr, _run
from ..util import (set_np, reset_np, is_np_array, is_np_shape,
                    np_array, np_shape, use_np)  # noqa: F401
from .. import random as _random  # noqa: F401

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape", "seed",
           "relu", "sigmoid", "softmax", "log_softmax", "activation",
           "batch_norm", "layer_norm", "fully_connected", "convolution",
           "pooling", "dropout", "embedding", "one_hot", "pick", "topk",
           "reshape_like", "arange_like", "gamma", "erf", "erfinv",
           "gelu", "leaky_relu", "batch_dot", "broadcast_like",
           "sequence_mask", "smooth_l1", "multibox_detection", "waitall"]

seed = _random.seed


def _np_out(r):
    if isinstance(r, list):
        return [x.as_np_ndarray() if isinstance(x, NDArray) else x
                for x in r]
    return r.as_np_ndarray() if isinstance(r, NDArray) else r


def _call(opname, *args, **kwargs):
    from .. import ndarray as F
    fn = getattr(F, opname)
    return _np_out(fn(*[_coerce_arr(a) for a in args], **kwargs))


def relu(data):
    return _call("relu", data)


def sigmoid(data):
    return _call("sigmoid", data)


def gelu(data):
    return _call("Activation", data, act_type="gelu")


def leaky_relu(data, gamma=0.01):
    return _call("LeakyReLU", data, act_type="leaky", slope=gamma)


def activation(data, act_type="relu"):
    return _call("Activation", data, act_type=act_type)


def softmax(data, axis=-1, length=None, temperature=None):
    kw = {"axis": axis}
    if temperature is not None:
        kw["temperature"] = temperature
    if length is not None:
        return _call("softmax", data, length, use_length=True, **kw)
    return _call("softmax", data, **kw)


def log_softmax(data, axis=-1):
    return _call("log_softmax", data, axis=axis)


def fully_connected(x, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    return _call("FullyConnected", x, weight,
                 *([] if no_bias or bias is None else [bias]),
                 num_hidden=num_hidden or weight.shape[0],
                 no_bias=no_bias or bias is None, flatten=flatten)


def convolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=0, num_group=1,
                no_bias=False, layout=None):
    args = [data, weight] + ([] if no_bias or bias is None else [bias])
    return _call("Convolution", *args, kernel=kernel,
                 stride=stride or (), dilate=dilate or (), pad=pad or (),
                 num_filter=num_filter, num_group=num_group,
                 no_bias=no_bias or bias is None,
                 layout=layout or "NCHW")


def pooling(data, kernel=(2, 2), pool_type="max", stride=None, pad=None,
            global_pool=False, **kwargs):
    return _call("Pooling", data, kernel=kernel, pool_type=pool_type,
                 stride=stride or (), pad=pad or (),
                 global_pool=global_pool, **kwargs)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    return _call("BatchNorm", x, gamma, beta, running_mean, running_var,
                 eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                 use_global_stats=use_global_stats, axis=axis)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _call("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def dropout(data, p=0.5, axes=(), mode="training"):
    return _call("Dropout", data, p=p, axes=axes, mode=mode)


def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    return _call("Embedding", data, weight,
                 input_dim=input_dim or weight.shape[0],
                 output_dim=output_dim or weight.shape[1])


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _call("one_hot", data, depth=depth, on_value=on_value,
                 off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    return _call("pick", data, index, axis=axis, keepdims=keepdims,
                 mode=mode)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    return _call("topk", data, axis=axis, k=k, ret_typ=ret_typ,
                 is_ascend=is_ascend)


def reshape_like(lhs, rhs):
    return _run("reshape_like", lambda x, y: jnp.reshape(x, y.shape),
                [lhs, rhs])


def arange_like(data, start=0.0, step=1.0, axis=None):
    def impl(x):
        n = x.size if axis is None else x.shape[axis]
        return start + step * jnp.arange(n, dtype=jnp.float32)
    return _run("arange_like", impl, [data])


def gamma(data):
    return _run("gamma", lambda x: jnp.exp(jax.lax.lgamma(x)), [data])


def erf(data):
    return _run("erf", jax.lax.erf, [data])


def erfinv(data):
    return _run("erfinv", jax.lax.erf_inv, [data])


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return _call("batch_dot", a, b, transpose_a=transpose_a,
                 transpose_b=transpose_b)


def broadcast_like(lhs, rhs):
    return _call("broadcast_like", lhs, rhs)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    args = [data] + ([sequence_length] if sequence_length is not None else [])
    return _call("SequenceMask", *args,
                 use_sequence_length=use_sequence_length, value=value,
                 axis=axis)


def smooth_l1(data, scalar=1.0):
    return _call("smooth_l1", data, scalar=scalar)


def multibox_detection(*args, **kwargs):
    raise NotImplementedError(
        "multibox_detection (SSD inference op) is not implemented; "
        "see mxnet_tpu.contrib for detection utilities")


def waitall():
    from ..ndarray import waitall as _w
    return _w()
