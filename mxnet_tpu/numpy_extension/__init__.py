"""``mx.npx`` — NumPy-extension namespace (reference
``python/mxnet/numpy_extension/`` + ``mx.npx`` op surface): neural-network
ops that have no NumPy equivalent, plus the ``set_np``/``reset_np``
semantics switches."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from ..ops.registry import Op, invoke
from ..numpy.multiarray import ndarray, _coerce_arr, _run
from ..util import (set_np, reset_np, is_np_array, is_np_shape,
                    np_array, np_shape, use_np)  # noqa: F401
from .. import random as _random  # noqa: F401

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape", "seed",
           "relu", "sigmoid", "softmax", "log_softmax", "activation",
           "batch_norm", "layer_norm", "fully_connected", "convolution",
           "pooling", "dropout", "embedding", "one_hot", "pick", "topk",
           "reshape_like", "arange_like", "gamma", "erf", "erfinv",
           "gelu", "leaky_relu", "batch_dot", "broadcast_like",
           "sequence_mask", "smooth_l1", "multibox_detection", "waitall"]

seed = _random.seed


def _np_out(r):
    if isinstance(r, list):
        return [x.as_np_ndarray() if isinstance(x, NDArray) else x
                for x in r]
    return r.as_np_ndarray() if isinstance(r, NDArray) else r


def _call(opname, *args, **kwargs):
    from .. import ndarray as F
    fn = getattr(F, opname)
    return _np_out(fn(*[_coerce_arr(a) for a in args], **kwargs))


def relu(data):
    return _call("relu", data)


def sigmoid(data):
    return _call("sigmoid", data)


def gelu(data):
    return _call("Activation", data, act_type="gelu")


def leaky_relu(data, gamma=0.01):
    return _call("LeakyReLU", data, act_type="leaky", slope=gamma)


def activation(data, act_type="relu"):
    return _call("Activation", data, act_type=act_type)


def softmax(data, axis=-1, length=None, temperature=None):
    kw = {"axis": axis}
    if temperature is not None:
        kw["temperature"] = temperature
    if length is not None:
        return _call("softmax", data, length, use_length=True, **kw)
    return _call("softmax", data, **kw)


def log_softmax(data, axis=-1):
    return _call("log_softmax", data, axis=axis)


def fully_connected(x, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    return _call("FullyConnected", x, weight,
                 *([] if no_bias or bias is None else [bias]),
                 num_hidden=num_hidden or weight.shape[0],
                 no_bias=no_bias or bias is None, flatten=flatten)


def convolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=0, num_group=1,
                no_bias=False, layout=None):
    args = [data, weight] + ([] if no_bias or bias is None else [bias])
    return _call("Convolution", *args, kernel=kernel,
                 stride=stride or (), dilate=dilate or (), pad=pad or (),
                 num_filter=num_filter, num_group=num_group,
                 no_bias=no_bias or bias is None,
                 layout=layout or "NCHW")


def pooling(data, kernel=(2, 2), pool_type="max", stride=None, pad=None,
            global_pool=False, **kwargs):
    return _call("Pooling", data, kernel=kernel, pool_type=pool_type,
                 stride=stride or (), pad=pad or (),
                 global_pool=global_pool, **kwargs)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    return _call("BatchNorm", x, gamma, beta, running_mean, running_var,
                 eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                 use_global_stats=use_global_stats, axis=axis)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _call("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def dropout(data, p=0.5, axes=(), mode="training"):
    return _call("Dropout", data, p=p, axes=axes, mode=mode)


def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    return _call("Embedding", data, weight,
                 input_dim=input_dim or weight.shape[0],
                 output_dim=output_dim or weight.shape[1])


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _call("one_hot", data, depth=depth, on_value=on_value,
                 off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    return _call("pick", data, index, axis=axis, keepdims=keepdims,
                 mode=mode)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    return _call("topk", data, axis=axis, k=k, ret_typ=ret_typ,
                 is_ascend=is_ascend)


def reshape_like(lhs, rhs):
    return _run("reshape_like", lambda x, y: jnp.reshape(x, y.shape),
                [lhs, rhs])


def arange_like(data, start=0.0, step=1.0, axis=None):
    def impl(x):
        n = x.size if axis is None else x.shape[axis]
        return start + step * jnp.arange(n, dtype=jnp.float32)
    return _run("arange_like", impl, [data])


def gamma(data):
    return _run("gamma", lambda x: jnp.exp(jax.lax.lgamma(x)), [data])


def erf(data):
    return _run("erf", jax.lax.erf, [data])


def erfinv(data):
    return _run("erfinv", jax.lax.erf_inv, [data])


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return _call("batch_dot", a, b, transpose_a=transpose_a,
                 transpose_b=transpose_b)


def broadcast_like(lhs, rhs):
    return _call("broadcast_like", lhs, rhs)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    args = [data] + ([sequence_length] if sequence_length is not None else [])
    return _call("SequenceMask", *args,
                 use_sequence_length=use_sequence_length, value=value,
                 axis=axis)


def smooth_l1(data, scalar=1.0):
    return _call("smooth_l1", data, scalar=scalar)


def multibox_detection(*args, **kwargs):
    raise NotImplementedError(
        "multibox_detection (SSD inference op) is not implemented; "
        "see mxnet_tpu.contrib for detection utilities")


def waitall():
    from ..ndarray import waitall as _w
    return _w()


def masked_softmax(data, mask=None, axis=-1, temperature=1.0):
    """Reference anchor ``npx.masked_softmax``: softmax with a boolean
    mask (False = excluded)."""
    import jax.numpy as jnp
    from ..ops.registry import Op, invoke

    def fn(x, *m):
        xs = x / temperature if temperature != 1.0 else x
        if m:
            xs = jnp.where(m[0].astype(bool), xs, -jnp.inf)
        out = jnp.exp(xs - jnp.max(xs, axis=axis, keepdims=True))
        out = jnp.where(jnp.isfinite(xs), out, 0.0)
        return out / jnp.maximum(out.sum(axis=axis, keepdims=True), 1e-12)

    args = [data] + ([mask] if mask is not None else [])
    return _np_out(invoke(Op(name="_npx_masked_softmax", fn=fn), args, {}))


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0):
    import jax.numpy as jnp
    from ..ops.registry import Op, invoke

    def fn(x, *m):
        xs = x / temperature if temperature != 1.0 else x
        if m:
            xs = jnp.where(m[0].astype(bool), xs, -jnp.inf)
        mx_ = jnp.max(xs, axis=axis, keepdims=True)
        lse = jnp.log(jnp.maximum(
            jnp.exp(xs - mx_).sum(axis=axis, keepdims=True), 1e-12)) + mx_
        return xs - lse

    args = [data] + ([mask] if mask is not None else [])
    return _np_out(invoke(Op(name="_npx_masked_log_softmax", fn=fn),
                          args, {}))


def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    return _call("GroupNorm", data, gamma, beta, num_groups=num_groups,
                 eps=eps)


def instance_norm(data, gamma, beta, eps=1e-3):
    return _call("InstanceNorm", data, gamma, beta, eps=eps)


def rms_norm(data, gamma, axis=-1, eps=1e-6):
    return _call("RMSNorm", data, gamma, axis=axis, eps=eps)


def gather_nd(data, indices):
    return _call("gather_nd", data, indices)


def scatter_nd(data, indices, shape):
    return _call("scatter_nd", data, indices, shape=shape)


def slice(data, begin, end, step=None):  # noqa: A001
    return _call("slice", data, begin=tuple(begin), end=tuple(end),
                 step=tuple(step) if step else None)


def slice_axis(data, axis, begin, end):
    return _call("slice_axis", data, axis=axis, begin=begin, end=end)


def stop_gradient(data):
    return _call("BlockGrad", data)


def index_update(data, indices, val):
    """Functional scatter-update (TPU-native: ``.at[].set``)."""
    import jax.numpy as jnp
    from ..ops.registry import Op, invoke
    idx = indices if isinstance(indices, tuple) else (indices,)

    def fn(x, v):
        return x.at[tuple(jnp.asarray(i) for i in idx)].set(v)

    return _np_out(invoke(Op(name="_npx_index_update", fn=fn),
                          [data, val], {}))


def index_add(data, indices, val):
    import jax.numpy as jnp
    from ..ops.registry import Op, invoke
    idx = indices if isinstance(indices, tuple) else (indices,)

    def fn(x, v):
        return x.at[tuple(jnp.asarray(i) for i in idx)].add(v)

    return _np_out(invoke(Op(name="_npx_index_add", fn=fn), [data, val], {}))


def foreach(body, data, init_states):
    """Reference anchor ``npx.foreach`` (control-flow op): scan ``body``
    over the leading axis.  TPU-native: ``lax.scan`` — compiled loop, no
    Python unrolling."""
    import jax
    from ..ndarray import NDArray

    multi_data = isinstance(data, (list, tuple))
    multi_states = isinstance(init_states, (list, tuple))
    xs = [d._data for d in data] if multi_data else data._data
    init = [s._data for s in init_states] if multi_states \
        else init_states._data

    def step(carry, x):
        x_nd = [NDArray(v) for v in x] if multi_data else NDArray(x)
        c_nd = [NDArray(v) for v in carry] if multi_states else NDArray(carry)
        out, new_states = body(x_nd, c_nd)
        out_raw = [o._data for o in out] if isinstance(out, (list, tuple)) \
            else out._data
        ns_raw = [s._data for s in new_states] if multi_states \
            else new_states._data
        return ns_raw, out_raw

    final, outs = jax.lax.scan(step, init, xs)
    wrap = lambda v: [_np_out_arr(x) for x in v] \
        if isinstance(v, (list, tuple)) else _np_out_arr(v)
    return wrap(outs), wrap(final)


def _np_out_arr(x):
    from ..numpy import ndarray as _np_ndarray
    return _np_ndarray(x)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Reference anchor ``npx.while_loop`` → ``lax.while_loop`` (with an
    iteration cap when given, matching the reference semantics)."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import NDArray

    raw = [v._data for v in loop_vars]

    def c(state):
        i, vs = state
        ok = cond([NDArray(v) for v in vs])
        ok = ok._data if hasattr(ok, "_data") else jnp.asarray(ok)
        ok = ok.reshape(()).astype(bool)
        if max_iterations is not None:
            ok = jnp.logical_and(ok, i < max_iterations)
        return ok

    def b(state):
        i, vs = state
        new = func([NDArray(v) for v in vs])
        return i + 1, tuple(v._data if hasattr(v, "_data") else v
                            for v in new)

    _, out = jax.lax.while_loop(c, b, (jnp.asarray(0), tuple(raw)))
    return [_np_out_arr(v) for v in out]


def cond(pred, then_func, else_func, inputs):
    """Reference anchor ``npx.cond`` → ``lax.cond``."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import NDArray

    p = pred._data if hasattr(pred, "_data") else jnp.asarray(pred)
    raw = [v._data for v in inputs]

    def t(vs):
        out = then_func([NDArray(v) for v in vs])
        return tuple(o._data for o in out) if isinstance(out, (list, tuple)) \
            else out._data

    def e(vs):
        out = else_func([NDArray(v) for v in vs])
        return tuple(o._data for o in out) if isinstance(out, (list, tuple)) \
            else out._data

    out = jax.lax.cond(p.reshape(()).astype(bool), t, e, tuple(raw))
    if isinstance(out, tuple):
        return [_np_out_arr(v) for v in out]
    return _np_out_arr(out)


def multinomial(data, shape=None, get_prob=False):
    from ..numpy import random as npr
    return npr.multinomial(1, data, size=shape)


def shuffle(data):
    from .. import random as _r
    return _np_out(_r.shuffle(data))


def load(fname):
    from ..ndarray import load as _l
    out = _l(fname)
    if isinstance(out, dict):
        return {k: _np_out(v) for k, v in out.items()}
    return [_np_out(v) for v in out]


def save(fname, data):
    from ..ndarray import save as _s
    return _s(fname, data)


import jax  # noqa: E402  (used by masked_softmax paths)


# device helpers (reference npx surface)
from ..context import cpu, gpu, num_gpus, current_context  # noqa: E402


def rnn(data, parameters, state, *args, **kwargs):
    """Fused RNN op under npx (delegates to the registered fused_rnn)."""
    from ..ndarray import fused_rnn as _fused
    return _fused(data, parameters, state, *args, **kwargs)
