"""Foundation: errors, env-var config, dtype tables.

TPU-native rebuild of the reference's dmlc-core facilities (SURVEY.md §3.1
"dmlc-core": logging/CHECK, `dmlc::GetEnv`, `dmlc::Parameter`) as one typed
Python config module (SURVEY.md §5.6).  `MXNET_*` environment variables keep
their reference names so existing user scripts and tests carry over.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

import numpy as onp

__all__ = [
    "MXNetError",
    "get_env",
    "env_truthy",
    "string_types",
    "numeric_types",
    "integer_types",
    "mx_real_t",
    "_Null",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference anchor: ``MXGetLastError`` /
    python ``MXNetError``)."""


# float32 matmuls run at full f32 precision (like the reference's fp32 cuBLAS
# gemm); bf16 speed comes from actual bf16 dtypes (AMP), not a hidden
# precision downgrade.  Override with MXNET_TPU_MATMUL_PRECISION=default for
# raw-speed f32 experiments.
import jax as _jax

_jax.config.update(
    "jax_default_matmul_precision",
    os.environ.get("MXNET_TPU_MATMUL_PRECISION", "highest"))


string_types = (str,)
numeric_types = (float, int, onp.generic)
integer_types = (int, onp.integer)

mx_real_t = onp.float32


class _NullType:
    """Placeholder for unset keyword arguments (reference anchor: ``_Null``
    in generated op wrappers)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "_Null"

    def __bool__(self):
        return False


_Null = _NullType()


# ---------------------------------------------------------------------------
# Environment-variable config (reference: dmlc::GetEnv at point of use;
# ~100 MXNET_* vars documented in docs/.../env_var.md).  We read lazily so
# tests can monkeypatch os.environ (mirrors mx.util.environment()).
# ---------------------------------------------------------------------------

_ENV_REGISTRY: dict[str, tuple[Any, str]] = {}
_env_lock = threading.Lock()


def register_env(name: str, default: Any, doc: str = "") -> None:
    with _env_lock:
        _ENV_REGISTRY[name] = (default, doc)


def get_env(name: str, default: Any = None, typ: Optional[Callable] = None):
    """Read an ``MXNET_*`` (or any) environment variable with typed parsing."""
    if default is None and name in _ENV_REGISTRY:
        default = _ENV_REGISTRY[name][0]
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is None and default is not None:
        typ = type(default)
    if typ is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if typ is not None:
        try:
            return typ(raw)
        except (TypeError, ValueError):
            return default
    return raw


def env_truthy(name: str, default: bool = False) -> bool:
    return bool(get_env(name, default, bool))


def parse_seconds(var: str, raw) -> Optional[float]:
    """LOUD seconds-knob parsing shared by the fault-tolerance timeout
    hatches (ISSUE 13: serve deadlines/step timeout, init/barrier
    timeouts, heartbeat interval): a malformed value raises a clean
    ``MXNetError`` naming the variable — never a silent fallback to a
    default or to wait-forever, which is exactly the hang/misconfig
    these knobs exist to prevent.  Returns ``None`` for an unset
    value; zero-vs-None semantics stay at the call site."""
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        raise MXNetError(f"{var}={raw!r}: expected seconds (a number)")


# Engine-type compat: MXNET_ENGINE_TYPE=NaiveEngine selects fully synchronous
# dispatch (reference anchor: NaiveEngine debug mode, SURVEY.md §5.2).  On
# TPU this means block_until_ready after every op.
register_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice",
             "NaiveEngine = synchronous dispatch for debugging")
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", 1, "no-op on TPU; XLA fuses")
register_env("MXNET_GPU_MEM_POOL_TYPE", "Naive", "no-op; XLA manages HBM")
# accepted-and-ignored CUDA/engine-era vars (docs/ENV_VARS.md "Data /
# misc"): registering them keeps ported scripts working AND keeps the
# tracelint TL005 docs<->reads reconciliation honest — every documented
# hatch has exactly one read/registration site.
register_env("MXNET_CUDNN_AUTOTUNE_DEFAULT", 1,
             "no-op; XLA autotunes convolutions itself")
register_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000,
             "no-op; collectives replace the kvstore server batching")
register_env("MXNET_USE_FUSION", 1, "no-op; XLA fusion is always on")
register_env("MXNET_GPU_WORKER_NTHREADS", 2,
             "no-op; XLA manages device streams")


def is_naive_engine() -> bool:
    return get_env("MXNET_ENGINE_TYPE") == "NaiveEngine"


# ---------------------------------------------------------------------------
# dtype tables (reference: mshadow type enum used across the C ABI)
# ---------------------------------------------------------------------------

_DTYPE_NP_TO_MX = {
    None: -1,
    onp.float32: 0,
    onp.float64: 1,
    onp.float16: 2,
    onp.uint8: 3,
    onp.int32: 4,
    onp.int8: 5,
    onp.int64: 6,
    onp.bool_: 7,
    onp.int16: 8,
    onp.uint16: 9,
    onp.uint32: 10,
    onp.uint64: 11,
}
_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}
# bfloat16 is TPU-native; give it the id the reference reserves for bf16.
try:  # ml_dtypes ships with jax
    import ml_dtypes

    bfloat16 = ml_dtypes.bfloat16
    _DTYPE_NP_TO_MX[bfloat16] = 12
    _DTYPE_MX_TO_NP[12] = bfloat16
except ImportError:  # pragma: no cover
    bfloat16 = None


def dtype_np_to_mx(dtype) -> int:
    key = onp.dtype(dtype).type if dtype is not None else None
    if key not in _DTYPE_NP_TO_MX:
        raise MXNetError(f"unsupported dtype {dtype}")
    return _DTYPE_NP_TO_MX[key]


def dtype_mx_to_np(code: int):
    return _DTYPE_MX_TO_NP[code]
