"""GPT-family decoder-only language models (the flagship perf model).

Reference counterpart: none in-tree (the reference's NLP stack is GluonNLP);
this corresponds to BASELINE config 5 ("GPT-2 774M TP×DP").  Design is
TPU-first: pre-norm blocks over flash attention, fused QKV, bf16-friendly,
and a Megatron-style tensor-parallel sharding rule set (``gpt_tp_rules``)
that GSPMD turns into ICI collectives.
"""
from __future__ import annotations

from dataclasses import dataclass


from ..gluon.block import HybridBlock
from ..gluon.nn.basic_layers import Dense, Dropout, Embedding, LayerNorm
from .transformer import TransformerDecoderCell

__all__ = ["GPTConfig", "GPT", "gpt2_small", "gpt2_medium", "gpt2_large",
           "gpt2_774m", "gpt_tp_rules"]


@dataclass
class GPTConfig:
    vocab_size: int = 50257
    max_length: int = 1024
    num_layers: int = 12
    units: int = 768
    num_heads: int = 12
    hidden_size: int = 3072
    dropout: float = 0.0
    dtype: str = "float32"

    @property
    def num_params(self) -> int:
        wpe = self.max_length * self.units
        wte = self.vocab_size * self.units
        per_layer = (3 * self.units * self.units + 3 * self.units  # qkv
                     + self.units * self.units + self.units        # proj
                     + 2 * self.units * self.hidden_size           # ffn
                     + self.hidden_size + self.units
                     + 4 * self.units)                             # 2×LN
        return wte + wpe + self.num_layers * per_layer + 2 * self.units


class GPT(HybridBlock):
    """Decoder-only transformer LM: tokens (B, L) → logits (B, L, vocab).

    The LM head reuses the token embedding (weight tying) — one big
    (B·L, units) × (units, vocab) MXU GEMM.
    """

    def __init__(self, config: GPTConfig, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._cfg = config
        c = config
        with self.name_scope():
            self.wte = Embedding(c.vocab_size, c.units, dtype=c.dtype,
                                 prefix="wte_")
            self.wpe = Embedding(c.max_length, c.units, dtype=c.dtype,
                                 prefix="wpe_")
            self.drop = Dropout(c.dropout) if c.dropout else None
            self.blocks = []
            for i in range(c.num_layers):
                cell = TransformerDecoderCell(
                    c.units, c.hidden_size, c.num_heads, c.dropout,
                    dtype=c.dtype,
                    prefix=f"h{i}_")
                self.register_child(cell, f"h{i}")
                self.blocks.append(cell)
            self.ln_f = LayerNorm(in_channels=c.units, prefix="lnf_")

    # weight tying (LM head = wte.T) reads a child's parameter directly, so
    # the whole model defines ``forward`` instead of ``hybrid_forward``;
    # hybridize still jits it (the CachedOp traces ``forward``).
    def forward(self, tokens, *args, **kwargs):
        from .. import ndarray as F
        B, L = tokens.shape
        x = self.wte(tokens)
        pos_ids = F.broadcast_to(
            F.reshape(F.arange(L, dtype="int32"), shape=(1, L)),
            shape=(B, L))
        x = x + self.wpe(pos_ids)
        if self.drop is not None:
            x = self.drop(x)
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        w = self.wte.weight.data()                       # (vocab, units)
        logits = F.dot(F.reshape(x, shape=(B * L, self._cfg.units)), w,
                       transpose_b=True)
        return F.reshape(logits, shape=(B, L, self._cfg.vocab_size))

    def stacked_decode_weights(self):
        """Every layer's decode weights stacked into (num_layers, ...)
        arrays (one array per slot: qkv/proj/fc1/fc2 weight+bias, the
        four LayerNorm rows) — the operand set of the stacked-layer
        ``lax.scan`` decode path in ``models.kv_generate``, which runs
        ONE layer-body's worth of HLO instead of ``num_layers`` unrolled
        copies.  See ``ops.decode_fused.stack_decode_weights``."""
        from ..ops.decode_fused import stack_decode_weights
        return stack_decode_weights(self.blocks)

    def generate(self, prompt_tokens, max_new_tokens=32, temperature=1.0,
                 top_k=0, seed=None):
        """Autoregressive sampling (greedy when ``temperature==0``;
        ``top_k>0`` restricts the sample space).  Host-driven loop over the
        growing prefix — jit caches one program per length like the
        reference's BucketingModule caches per-bucket graphs."""
        import numpy as np
        from .. import ndarray as nd

        rng = np.random.RandomState(seed if seed is not None else 0)
        out = np.asarray(
            prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
            else prompt_tokens, dtype=np.int32)
        for _ in range(max_new_tokens):
            window = out[:, -self._cfg.max_length:]
            logits = self(nd.array(window, dtype="int32"))
            last = logits.asnumpy()[:, -1].astype(np.float64)   # (B, V)
            if temperature == 0.0:
                nxt = last.argmax(-1).astype(np.int32)
            else:
                last = last / max(temperature, 1e-6)
                if top_k and top_k < last.shape[-1]:
                    kth = np.partition(last, -top_k, axis=-1)[:, -top_k]
                    last = np.where(last < kth[:, None], -np.inf, last)
                p = np.exp(last - last.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                nxt = np.asarray([rng.choice(p.shape[-1], p=row)
                                  for row in p], dtype=np.int32)
            out = np.concatenate([out, nxt[:, None]], axis=1)
        return out


def gpt_tp_rules(tp_axis: str = "tp"):
    """Megatron-style TP sharding: QKV/fc1 split on the output dim, proj/fc2
    on the input dim (one all-reduce per block pair, inserted by GSPMD);
    embeddings sharded on vocab."""
    from ..parallel import ShardingRules, P
    return ShardingRules([
        (r".*attn_qkv_weight", P(tp_axis, None)),
        (r".*attn_qkv_bias", P(tp_axis)),
        (r".*attn_out_weight", P(None, tp_axis)),
        (r".*ffn_fc1_weight", P(tp_axis, None)),
        (r".*ffn_fc1_bias", P(tp_axis)),
        (r".*ffn_fc2_weight", P(None, tp_axis)),
        (r".*wte_weight", P(tp_axis, None)),
    ])


def _preset(**kw):
    def make(**overrides):
        cfg = GPTConfig(**{**kw, **overrides})
        return GPT(cfg), cfg
    return make


gpt2_small = _preset(num_layers=12, units=768, num_heads=12,
                     hidden_size=3072)
gpt2_medium = _preset(num_layers=24, units=1024, num_heads=16,
                      hidden_size=4096)
gpt2_large = _preset(num_layers=36, units=1280, num_heads=20,
                     hidden_size=5120)
gpt2_774m = gpt2_large  # BASELINE config 5 naming
