"""Model families (transformers).

The reference's transformer-era surface lives in GluonNLP (external) plus the
contrib fused-attention ops (SURVEY.md §3.1 contrib family,
``_contrib_interleaved_matmul_selfatt_*``).  Here the transformer family is
first-class: hybridizable Gluon blocks whose attention runs the flash
kernel (ops/attention.py) and whose layouts are MXU-shaped (fused QKV
matmul, big batched GEMMs).  Vision models live in
``gluon.model_zoo.vision``.
"""
from .transformer import (MultiHeadAttention, PositionwiseFFN,
                          TransformerEncoderCell, TransformerDecoderCell)
from .decoding import kv_generate, decode_mode, decode_step_program
from .gpt import GPT, GPTConfig, gpt2_small, gpt2_medium, gpt2_large, \
    gpt2_774m, gpt_tp_rules
from .bert import BERTModel, BERTConfig, bert_base, bert_large
from .llama import (Llama, LlamaConfig, llama_tp_rules, llama_tiny,
                    llama_7b)
from .seq2seq import (CrossAttention, Seq2SeqEncoder, Seq2SeqDecoder,
                      Seq2SeqDecoderCell, TransformerSeq2Seq)

__all__ = [
    "MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
    "TransformerDecoderCell", "GPT", "GPTConfig", "gpt2_small",
    "gpt2_medium", "gpt2_large", "gpt2_774m", "gpt_tp_rules",
    "BERTModel", "BERTConfig", "bert_base", "bert_large",
    "CrossAttention", "Seq2SeqEncoder", "Seq2SeqDecoder",
    "Seq2SeqDecoderCell", "TransformerSeq2Seq",
    "Llama", "LlamaConfig", "llama_tp_rules", "llama_tiny", "llama_7b",
    "kv_generate", "decode_mode", "decode_step_program",
]
