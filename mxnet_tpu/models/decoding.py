"""KV-cache incremental decoding for GPT models.

``GPT.generate`` recomputes the full prefix for every new token (O(L²) per
token, one jit program per prefix length — the BucketingModule analog).
``kv_generate`` is the TPU-native decoder: a fixed-shape per-layer K/V
cache updated with ``lax.dynamic_update_slice``, the WHOLE decode loop
(prefill + sampling) compiled as ONE ``lax.scan`` program — no per-token
dispatch, no retraces, O(L) work per token.

Reference counterpart: none in-tree (GluonNLP-era beam/sampling ran the
full-prefix path); this is a NEW capability like flash/ring attention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

__all__ = ["kv_generate"]


def _ln(x, g, b, eps=1e-5):
    # matches ops.nn.LayerNorm: f32 statistics, rsqrt, original dtype out
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.float16,
                                               jnp.bfloat16) else x
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * lax.rsqrt(var + eps)
    return (out * g.astype(out.dtype) + b.astype(out.dtype)).astype(x.dtype)


def _gather_params(gpt):
    """Pull the weight arrays out of the Block tree (raw jax arrays)."""
    p = {}
    p["wte"] = gpt.wte.weight.data()._data
    p["wpe"] = gpt.wpe.weight.data()._data
    p["lnf_g"] = gpt.ln_f.gamma.data()._data
    p["lnf_b"] = gpt.ln_f.beta.data()._data
    layers = []
    for blk in gpt.blocks:
        layers.append({
            "ln1_g": blk.ln1.gamma.data()._data,
            "ln1_b": blk.ln1.beta.data()._data,
            "wqkv": blk.attn.qkv.weight.data()._data,    # (3U, U)
            "bqkv": blk.attn.qkv.bias.data()._data,
            "wproj": blk.attn.proj.weight.data()._data,  # (U, U)
            "bproj": blk.attn.proj.bias.data()._data,
            "ln2_g": blk.ln2.gamma.data()._data,
            "ln2_b": blk.ln2.beta.data()._data,
            "w1": blk.ffn.fc1.weight.data()._data,       # (FF, U)
            "b1": blk.ffn.fc1.bias.data()._data,
            "w2": blk.ffn.fc2.weight.data()._data,       # (U, FF)
            "b2": blk.ffn.fc2.bias.data()._data,
        })
    p["layers"] = layers
    return p


def kv_generate(gpt, prompt_tokens, max_new_tokens=32, temperature=1.0,
                top_k=0, seed=0):
    """Sample ``max_new_tokens`` continuations for a (B, P) prompt.

    Greedy when ``temperature == 0``; ``top_k > 0`` restricts the sample
    space.  Matches ``GPT.generate`` token-for-token in greedy mode (the
    KV-cached attention is mathematically identical to full recompute).
    Returns the full (B, P + max_new_tokens) int32 array.
    """
    cfg = gpt._cfg
    H, U = cfg.num_heads, cfg.units
    D = U // H
    prompt = onp.asarray(
        prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
        else prompt_tokens, dtype=onp.int32)
    B, P = prompt.shape
    total = P + max_new_tokens
    if total > cfg.max_length:
        raise ValueError(f"prompt+new = {total} exceeds max_length "
                         f"{cfg.max_length}")
    params = _gather_params(gpt)
    NL = len(params["layers"])
    cdtype = params["wte"].dtype
    scale = 1.0 / (D ** 0.5)

    # the compiled decode program is cached on the model instance — a
    # fresh jax.jit per call would recompile every time (params/prompt/key
    # are traced ARGUMENTS, so weight updates do not invalidate the cache)
    cache_key = (B, P, max_new_tokens, float(temperature), int(top_k),
                 str(cdtype))
    cache = gpt.__dict__.setdefault("_kv_decode_cache", {})

    def one_token(params, x_tok, pos, ck, cv):
        """x_tok (B,) int32 at position pos -> (logits (B,V), new caches).
        ck/cv: (NL, B, H, maxT, D)."""
        x = params["wte"][x_tok] + params["wpe"][pos]          # (B, U)
        idx = lax.broadcasted_iota(jnp.int32, (1, 1, total), 2)
        for i, ly in enumerate(params["layers"]):
            h = _ln(x, ly["ln1_g"], ly["ln1_b"])
            qkv = h @ ly["wqkv"].T + ly["bqkv"]                # (B, 3U)
            q, k, v = (qkv[:, j * U:(j + 1) * U].reshape(B, H, 1, D)
                       for j in range(3))
            ck = lax.dynamic_update_slice(ck, k[None], (i, 0, 0, pos, 0))
            cv = lax.dynamic_update_slice(cv, v[None], (i, 0, 0, pos, 0))
            s = jnp.einsum("bhqd,bhtd->bhqt", q, ck[i],
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(idx <= pos, s[:, :, 0], -1e30)        # (B,H,T)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bht,bhtd->bhd", p, cv[i])
            o = o.reshape(B, U) @ ly["wproj"].T + ly["bproj"]
            x = x + o
            h2 = _ln(x, ly["ln2_g"], ly["ln2_b"])
            f = jax.nn.gelu(h2 @ ly["w1"].T + ly["b1"])  # tanh-approx, matches Activation("gelu")
            x = x + (f @ ly["w2"].T + ly["b2"])
        x = _ln(x, params["lnf_g"], params["lnf_b"])
        logits = (x @ params["wte"].T).astype(jnp.float32)      # (B, V)
        return logits, ck, cv

    if cache_key not in cache:
        def run(params, prompt_dev, key0):
            def scan_body(carry, t):
                tok, ck, cv = carry
                # teacher-force while t is inside the prompt
                cur = jnp.where(t < P, prompt_dev[:, jnp.minimum(t, P - 1)],
                                tok)
                logits, ck, cv = one_token(params, cur, t, ck, cv)
                if temperature == 0.0:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    lg = logits / max(float(temperature), 1e-6)
                    if top_k and top_k < lg.shape[-1]:
                        kth = jax.lax.top_k(lg, top_k)[0][:, -1]
                        lg = jnp.where(lg < kth[:, None], -jnp.inf, lg)
                    nxt = jax.random.categorical(
                        jax.random.fold_in(key0, t), lg,
                        axis=-1).astype(jnp.int32)
                return (nxt, ck, cv), nxt

            ck = jnp.zeros((NL, B, H, total, D), cdtype)
            cv = jnp.zeros((NL, B, H, total, D), cdtype)
            tok0 = jnp.zeros((B,), jnp.int32)
            (_, _, _), toks = lax.scan(scan_body, (tok0, ck, cv),
                                       jnp.arange(total - 1))
            return toks                                        # (T-1, B)

        cache[cache_key] = jax.jit(run)

    toks = onp.asarray(cache[cache_key](
        params, jnp.asarray(prompt), jax.random.PRNGKey(seed))).T
    # positions P-1 .. total-2 sampled the new tokens
    new = toks[:, P - 1:]
    return onp.concatenate([prompt, new], axis=1)
