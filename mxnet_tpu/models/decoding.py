"""KV-cache incremental decoding for transformer-decoder models.

``GPT.generate`` recomputes the full prefix for every new token (O(L²) per
token, one jit program per prefix length — the BucketingModule analog).
``kv_generate`` is the TPU-native decoder: a fixed-shape per-layer K/V
cache updated with ``lax.dynamic_update_slice``, the WHOLE decode loop
(prefill + sampling) compiled as ONE ``lax.scan`` program — no per-token
dispatch, no retraces, O(L) work per token.

r3 generalization (VERDICT r2 item 8): the per-layer math is DERIVED FROM
THE MODEL'S OWN BLOCKS — ``ln1``/``attn.qkv``/``attn.proj``/``ln2``/
``ffn``/``ln_f`` are invoked as Gluon layers on traced values (weights are
traced arguments via the same swap discipline as ``SPMDTrainer``), so a
model variant that changes normalization, activation, or bias structure
inside those sublayers decodes correctly with no decoder change.  Only the
cache-attention core (one-token query against the running K/V cache) is
decoder-specific math.

Decodable protocol — two block families are recognized:
- GPT/_TransformerCell: ``wte``+``wpe`` embeddings, blocks with ``ln1``,
  ``attn`` (fused ``qkv``+``proj``), ``ln2``, ``ffn``;
- Llama: ``wte`` only (RoPE applied per step via the ``rope`` op's
  ``position_offset``), blocks with ``rms1``, ``attn`` (separate
  ``q_proj``/``k_proj``/``v_proj``/``o_proj``, grouped-query kv heads),
  ``rms2``, ``mlp``.
Final norm is ``ln_f``; the head is a ``head``/``lm_head`` Block or the
tied ``wte`` weight.  In all cases the norm/projection/FFN math comes
from the model's OWN sublayers.

Reference counterpart: none in-tree (GluonNLP-era beam/sampling ran the
full-prefix path); this is a NEW capability like flash/ring attention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

__all__ = ["kv_generate"]


def _call(layer, *vals):
    """Invoke a Gluon (Hybrid)Block imperatively on traced jax values."""
    from ..gluon.block import _no_hybrid
    from ..ndarray.ndarray import NDArray
    from .. import autograd

    with autograd.pause(train_mode=False), _no_hybrid():
        out = layer(*[v if isinstance(v, NDArray) else NDArray(v)
                      for v in vals])
    return out._data if isinstance(out, NDArray) else out


def _quantize_rows(w):
    """Per-output-channel symmetric int8 quantization: w (out, in) →
    (int8 codes TRANSPOSED to (in, out) for the streaming kernel's
    canonical matmul layout, f32 scales (out,)).  bf16 exactly represents
    every int in [-127, 127], so the in-dot convert loses nothing;
    accumulation runs f32 via ``preferred_element_type``."""
    w32 = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(w32), axis=1) / 127.0, 1e-8)
    wq = jnp.round(w32 / s[:, None]).astype(jnp.int8)
    return wq.T.copy(), s


def _quantize_head(w, bias=None):
    """Head quantization with the vocab dim padded to the 128 lane tile:
    GPT-2's 50257 is not a lane multiple, and an unpadded head silently
    falls back to the dequantizing XLA einsum (measured 8x slower than
    bf16) for the LARGEST matmul of every decode step.  Pads codes with
    zeros and scales with 1.0 (padded logits come out 0 and are sliced
    off by the caller, which tracks the true vocab statically); returns
    (codes, scales, bias_or_None)."""
    wq, s = _quantize_rows(w)
    pad = (-wq.shape[1]) % 128
    if pad:
        wq = jnp.pad(wq, ((0, 0), (0, pad)))
        s = jnp.pad(s, (0, pad), constant_values=1.0)
        if bias is not None:
            bias = jnp.pad(bias.astype(jnp.float32), (0, pad))
    return wq, s, bias


def kv_generate(model, prompt_tokens, max_new_tokens=32, temperature=1.0,
                top_k=0, seed=0, prefill="batched", weights="native",
                fused="auto"):
    """Sample ``max_new_tokens`` continuations for a (B, P) prompt.

    Greedy when ``temperature == 0``; ``top_k > 0`` restricts the sample
    space (sampling uses ``jax.random.categorical`` with a per-step
    ``fold_in(key, t)`` key — deterministic given ``seed``).  Matches
    ``model.generate`` token-for-token in greedy mode (the KV-cached
    attention is mathematically identical to full recompute).  Returns
    the full (B, P + max_new_tokens) int32 array.

    ``prefill``: ``"batched"`` (default) runs the whole prompt through
    ONE causal forward that fills the K/V cache — P-1 sequential scan
    steps collapse into one MXU-shaped pass; ``"scan"`` keeps the
    token-at-a-time prefill (same token stream either way — the sampling
    key at position t is ``fold_in(key, t)`` in both modes).

    ``weights``: ``"int8"`` streams the decode-step matmul weights as
    per-channel-quantized int8 (half the HBM bytes of bf16),
    dequantizing inside the dot with f32 accumulation.  Both families
    (GPT fused-QKV and Llama split-projection/SwiGLU).  An approximate
    path — greedy tokens can differ from the exact native path (~0.4%
    weight error); measured r4: the decode step is sequencer-bound at
    GPT-2-small size, so int8's byte savings pay off only on larger
    models (BASELINE.md decode section).

    ``fused``: ``"auto"`` (default) runs the decode scan step through
    the ONE-kernel-per-token Pallas path (ops/decode_fused.py — the
    r4-measured ~230-op sequencer overhead collapses to ~10 ops) when
    the model qualifies (GPT family, bf16, batch <= 4, tileable dims,
    native weights, TPU backend); ``"on"`` requires it (raises if
    unsupported); ``"off"`` keeps the per-op XLA scan step.  Hidden
    states can differ from the unfused path by ~1 bf16 ulp (chunked
    f32 accumulation order in fc2) — greedy token parity is asserted
    in tests on the covered model sizes.
    """
    cfg = model._cfg
    H = cfg.num_heads
    U = cfg.units
    D = U // H
    # family detection (see module docstring): Llama cells carry separate
    # projections + RoPE and may use fewer kv heads (GQA)
    is_llama = hasattr(model.blocks[0], "rms1")
    KV = getattr(cfg, "num_kv_heads", H) if is_llama else H
    rope_base = float(getattr(cfg, "rope_base", 10000.0))
    if prefill not in ("batched", "scan"):
        raise ValueError(f"prefill must be 'batched' or 'scan', "
                         f"got {prefill!r}")
    if weights not in ("native", "int8"):
        raise ValueError(f"weights must be 'native' or 'int8', "
                         f"got {weights!r}")
    use_int8 = weights == "int8"
    prompt = onp.asarray(
        prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
        else prompt_tokens, dtype=onp.int32)
    B, P = prompt.shape
    if max_new_tokens <= 0:
        return prompt.copy()
    total = P + max_new_tokens
    if total > cfg.max_length:
        raise ValueError(f"prompt+new = {total} exceeds max_length "
                         f"{cfg.max_length}")

    # weights ride as TRACED ARGUMENTS (swap discipline shared with
    # SPMDTrainer._forward_loss): updates to the model do NOT invalidate
    # the compiled decode program
    params = [p for p in model.collect_params().values()
              if p._data is not None]
    param_vals = [p._data._data for p in params]
    NL = len(model.blocks)
    cdtype = model.wte.weight.data()._data.dtype
    scale = 1.0 / (D ** 0.5)
    head = getattr(model, "head", None) or getattr(model, "lm_head", None)

    # -- fused one-kernel-per-token path (ops/decode_fused.py) --------- #
    use_fused = False
    act_t = None
    ln_eps = 1e-5
    if fused not in ("auto", "on", "off"):
        raise ValueError(f"fused must be 'auto', 'on' or 'off', "
                         f"got {fused!r}")
    if fused != "off":
        from ..ops.decode_fused import fused_decode_supported
        if is_llama:
            ln_eps = float(getattr(model.blocks[0].rms1, "_eps", 1e-6))
            use_fused = fused_decode_supported(cfg, B, total, cdtype)
        else:
            act_t = getattr(model.blocks[0].ffn.fc1.act, "_act_type",
                            None) \
                if model.blocks[0].ffn.fc1.act is not None else None
            ln_eps = float(getattr(model.blocks[0].ln1, "_eps", 1e-5))
            use_fused = (act_t in (None, "gelu", "relu")
                         and fused_decode_supported(cfg, B, total,
                                                    cdtype))
    if fused == "on" and not use_fused:
        from ..base import MXNetError
        raise MXNetError(
            "fused='on' but the fused decode kernel does not support "
            "this model/batch/dtype (see ops/decode_fused.py "
            "fused_decode_supported)")
    packed = None
    if use_fused:
        from ..ops.decode_fused import (pack_gpt_weights,
                                        pack_llama_weights)
        fcache = model.__dict__.setdefault("_fused_decode_cache", {})
        srcs = [use_int8]
        for blk in model.blocks:
            if is_llama:
                lyrs = (blk.attn.q_proj, blk.attn.k_proj,
                        blk.attn.v_proj, blk.attn.o_proj,
                        blk.mlp.gate, blk.mlp.up, blk.mlp.down)
                lnls = (blk.rms1, blk.rms2)
            else:
                lyrs = (blk.attn.qkv, blk.attn.proj, blk.ffn.fc1,
                        blk.ffn.fc2)
                lnls = (blk.ln1, blk.ln2)
            for lyr in lyrs:
                srcs.append(lyr.weight.data()._data)
                if getattr(lyr, "bias", None) is not None:
                    srcs.append(lyr.bias.data()._data)
            for lnl in lnls:
                srcs.append(lnl.gamma.data()._data)
                if getattr(lnl, "beta", None) is not None:
                    srcs.append(lnl.beta.data()._data)
        cached = fcache.get("srcs")
        if cached is None or len(cached) != len(srcs) or \
                not all(a is b for a, b in zip(cached, srcs)):
            # pinned-source invalidation discipline shared with the q8
            # cache above: train steps rebind arrays -> repack
            fcache["srcs"] = srcs
            fcache["val"] = (
                pack_llama_weights(model.blocks, cfg, cdtype,
                                   quant=use_int8) if is_llama
                else pack_gpt_weights(model.blocks, cdtype,
                                      quant=use_int8))
        packed = fcache["val"]

    cache_key = (B, P, max_new_tokens, float(temperature), int(top_k),
                 str(cdtype), prefill, weights, use_fused)
    cache = model.__dict__.setdefault("_kv_decode_cache", {})

    # -- int8 weight streaming: quantize the decode matmul weights ------ #
    # codes/scales ride as traced args beside the params, so the compiled
    # program is reusable after weight updates
    from ..ops.registry import get_op
    _act_fn = get_op("Activation").fn
    q8v = None
    fc1_act = None
    if use_int8:
        if not is_llama:
            fc1_act = getattr(model.blocks[0].ffn.fc1.act, "_act_type",
                              None) \
                if model.blocks[0].ffn.fc1.act is not None else None
        # cache the codes keyed on the SOURCE ARRAYS THEMSELVES (weights
        # AND biases), compared by `is` against pinned strong refs — a
        # train step rebinds the arrays and triggers requantization,
        # while repeated generate calls reuse the codes.  Pinning the
        # sources (not id() snapshots) is load-bearing: freed buffer
        # addresses get recycled by CPython, so an id()-keyed cache can
        # silently serve stale codes after an update.
        head_w = (head.weight if head is not None
                  else model.wte.weight).data()._data
        head_vocab = int(head_w.shape[0])
        head_b = None
        if head is not None and getattr(head, "bias", None) is not None:
            head_b = head.bias.data()._data
        if is_llama:
            lyr_tabs = [{"q": blk.attn.q_proj, "k": blk.attn.k_proj,
                         "v": blk.attn.v_proj, "o": blk.attn.o_proj,
                         "gate": blk.mlp.gate, "up": blk.mlp.up,
                         "down": blk.mlp.down} for blk in model.blocks]
        else:
            lyr_tabs = [{"qkv": blk.attn.qkv, "proj": blk.attn.proj,
                         "fc1": blk.ffn.fc1, "fc2": blk.ffn.fc2}
                        for blk in model.blocks]
        srcs = [l.weight.data()._data for t in lyr_tabs
                for l in t.values()]
        srcs += [l.bias.data()._data for t in lyr_tabs
                 for l in t.values()
                 if getattr(l, "bias", None) is not None]
        srcs.append(head_w)
        if head_b is not None:
            srcs.append(head_b)
        q8_cache = model.__dict__.setdefault("_q8_weight_cache", {})
        cached = q8_cache.get("srcs")
        if cached is None or len(cached) != len(srcs) or \
                not all(a is b for a, b in zip(cached, srcs)):
            def _q(lyr):
                wq, s = _quantize_rows(lyr.weight.data()._data)
                b = None
                if getattr(lyr, "bias", None) is not None:
                    b = lyr.bias.data()._data
                return (wq, s, b)

            q8_cache["srcs"] = srcs
            q8_cache["val"] = {
                "blocks": [{k: _q(l) for k, l in t.items()}
                           for t in lyr_tabs],
                "head": _quantize_head(head_w, head_b),
            }
        q8v = q8_cache["val"]

    def _dense_q8(x, ent, act_type=None):
        """Weight-only int8 matvec via the Pallas streaming kernel: int8
        codes convert to bf16 IN VMEM (exact for |code| ≤ 127), f32 MXU
        accumulation, per-channel rescale."""
        from ..ops.q8_matvec import q8_matvec
        wq, s, b = ent
        y = q8_matvec(x, wq, s, b).astype(cdtype)
        if act_type:
            y = _act_fn(y, act_type=act_type)
        return y

    def _sample(logits, t, key0):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # temperature is a python-scalar closure capture, not an operand:
        # tracelint: disable=TL001 -- scalar cast folds at trace time
        lg = logits / max(float(temperature), 1e-6)
        if top_k and top_k < lg.shape[-1]:
            kth = jax.lax.top_k(lg, top_k)[0][:, -1]
            lg = jnp.where(lg < kth[:, None], -jnp.inf, lg)
        return jax.random.categorical(
            jax.random.fold_in(key0, t), lg, axis=-1).astype(jnp.int32)

    def one_token(x_tok, pos, ck, cv, q8=None):
        """x_tok (B,) int32 at position pos -> (logits (B,V), new caches).
        ck/cv: (NL, B, KV, maxT, D).  All layer math comes from the
        model's own sublayers; only the cached-attention core (and RoPE
        application for Llama) is inlined."""
        from ..ops.attention import rope as _rope

        x = _call(model.wte, x_tok)
        if not is_llama:
            x = x + _call(model.wpe, jnp.broadcast_to(pos, (B,)))
        idx = lax.broadcasted_iota(jnp.int32, (1, 1, total), 2)
        for i, blk in enumerate(model.blocks):
            # one copy of the projection math for both weight modes
            def _lin(layer, kind, h):
                return _dense_q8(h, q8["blocks"][i][kind]) \
                    if q8 is not None else _call(layer, h)

            if is_llama:
                h = _call(blk.rms1, x)
                q = _lin(blk.attn.q_proj, "q", h).reshape(B, H, 1, D)
                k = _lin(blk.attn.k_proj, "k", h).reshape(B, KV, 1, D)
                v = _lin(blk.attn.v_proj, "v", h).reshape(B, KV, 1, D)
                q = _rope.__wrapped__(q, base=rope_base,
                                      position_offset=pos)
                k = _rope.__wrapped__(k, base=rope_base,
                                      position_offset=pos)
            else:
                h = _call(blk.ln1, x)
                qkv = _dense_q8(h, q8["blocks"][i]["qkv"]) if q8 is not None \
                    else _call(blk.attn.qkv, h)               # (B, 3U)
                q, k, v = (qkv[:, j * U:(j + 1) * U].reshape(B, H, 1, D)
                           for j in range(3))
            ck = lax.dynamic_update_slice(ck, k[None], (i, 0, 0, pos, 0))
            cv = lax.dynamic_update_slice(cv, v[None], (i, 0, 0, pos, 0))
            kc, vc = ck[i], cv[i]                             # (B,KV,T,D)
            # grouped einsums contract q's head groups directly against
            # the KV-head cache — no materialized H-head repeat (the GQA
            # memory-bandwidth benefit is the point of the small cache)
            qg = q.reshape(B, KV, H // KV, D)
            s = jnp.einsum("bkgd,bktd->bkgt", qg, kc,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(idx[:, :, None] <= pos, s, -1e30)   # (B,KV,G,T)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bkgt,bktd->bkgd", p, vc).reshape(B, U)
            if is_llama:
                x = x + _lin(blk.attn.o_proj, "o", o)
                h2 = _call(blk.rms2, x)
                if q8 is not None:
                    # SwiGLU decomposed: down(silu(gate)·up), matching
                    # models/llama.py (the native arm calls the whole
                    # mlp Block so model variants keep working)
                    g = _lin(blk.mlp.gate, "gate", h2)
                    u = _lin(blk.mlp.up, "up", h2)
                    x = x + _lin(blk.mlp.down, "down",
                                 g * jax.nn.sigmoid(g) * u)
                else:
                    x = x + _call(blk.mlp, h2)
            elif q8 is not None:
                x = x + _dense_q8(o, q8["blocks"][i]["proj"])
                h2 = _call(blk.ln2, x)
                x = x + _dense_q8(_dense_q8(h2, q8["blocks"][i]["fc1"],
                                            fc1_act),
                                  q8["blocks"][i]["fc2"])
            else:
                x = x + _call(blk.attn.proj, o)
                x = x + _call(blk.ffn, _call(blk.ln2, x))
        x = _call(model.ln_f, x)
        if q8 is not None:
            from ..ops.q8_matvec import q8_matvec
            hwq, hs, hb = q8["head"]
            # slice the 128-padded vocab back down; the true vocab is a
            # STATIC closure value (an int in the traced pytree would
            # arrive as a tracer and break the slice)
            logits = q8_matvec(x, hwq, hs, hb)[:, :head_vocab]
        elif head is not None:
            logits = _call(head, x).astype(jnp.float32)
        else:  # tied-embedding head
            w = model.wte.weight.data()._data                 # traced (swap)
            logits = (x @ w.T).astype(jnp.float32)
        return logits, ck, cv

    def fused_token(x_tok, pos, ck, cv, packed_t, q8=None):
        """one_token's fused twin: embeddings and head stay XLA ops;
        every transformer layer runs inside ONE Pallas kernel
        (ops/decode_fused.py decode_step).  In int8 mode the layer
        stream is int8 codes and the head goes through q8_matvec, same
        as the unfused q8 path."""
        from ..ops.decode_fused import decode_step

        x = _call(model.wte, x_tok)
        if not is_llama:
            x = x + _call(model.wpe, jnp.broadcast_to(pos, (B,)))
        x, ck, cv = decode_step(pos, x, packed_t, ck, cv, cfg,
                                act_t, ln_eps)
        xl = _call(model.ln_f, x)
        if q8 is not None:
            from ..ops.q8_matvec import q8_matvec
            hwq, hs, hb = q8["head"]
            logits = q8_matvec(xl, hwq, hs, hb)[:, :head_vocab]
        elif head is not None:
            logits = _call(head, xl).astype(jnp.float32)
        else:
            w = model.wte.weight.data()._data
            logits = (xl @ w.T).astype(jnp.float32)
        return logits, ck, cv

    def prefill_batch(prompt_dev, ck, cv):
        """One causal forward over the whole (B, P) prompt: fills cache
        positions [0, P) and returns the position-P-1 logits.  Exact same
        math as the per-token path (einsum + f32 softmax), reshaped onto
        MXU-friendly (B·P, ·) GEMMs."""
        from ..ops.attention import rope as _rope

        from ..ops.registry import get_op
        _flash_fn = get_op("flash_attention").fn

        x = _call(model.wte, prompt_dev)                      # (B, P, U)
        if not is_llama:
            pos = jnp.arange(P, dtype=jnp.int32)
            x = x + _call(model.wpe, jnp.broadcast_to(pos[None], (B, P)))
        for i, blk in enumerate(model.blocks):
            if is_llama:
                h = _call(blk.rms1, x)
                q = _call(blk.attn.q_proj, h).reshape(
                    B, P, H, D).transpose(0, 2, 1, 3)
                k = _call(blk.attn.k_proj, h).reshape(
                    B, P, KV, D).transpose(0, 2, 1, 3)
                v = _call(blk.attn.v_proj, h).reshape(
                    B, P, KV, D).transpose(0, 2, 1, 3)
                q = _rope.__wrapped__(q, base=rope_base, position_offset=0)
                k = _rope.__wrapped__(k, base=rope_base, position_offset=0)
            else:
                h = _call(blk.ln1, x)
                qkv = _call(blk.attn.qkv, h)                  # (B, P, 3U)
                q, k, v = (qkv[..., j * U:(j + 1) * U]
                           .reshape(B, P, H, D).transpose(0, 2, 1, 3)
                           for j in range(3))
            ck = lax.dynamic_update_slice(
                ck, k.astype(cdtype)[None], (i, 0, 0, 0, 0))
            cv = lax.dynamic_update_slice(
                cv, v.astype(cdtype)[None], (i, 0, 0, 0, 0))
            # causal attention over the prompt via the flash kernel —
            # O(P) memory (no (P, P) score tensor), so long prompts
            # prefill without OOM; GQA repeats k/v across head groups
            kf, vf = k, v
            if KV != H:
                kf = jnp.repeat(k, H // KV, axis=1)
                vf = jnp.repeat(v, H // KV, axis=1)
            o = _flash_fn(q, kf, vf, None, scale=scale, causal=True)
            o = o.transpose(0, 2, 1, 3).reshape(B, P, U)
            if is_llama:
                x = x + _call(blk.attn.o_proj, o)
                x = x + _call(blk.mlp, _call(blk.rms2, x))
            else:
                x = x + _call(blk.attn.proj, o)
                x = x + _call(blk.ffn, _call(blk.ln2, x))
        xl = _call(model.ln_f, x[:, -1])
        if head is not None:
            logits = _call(head, xl).astype(jnp.float32)
        else:
            w = model.wte.weight.data()._data
            logits = (xl @ w.T).astype(jnp.float32)
        return logits, ck, cv

    if cache_key not in cache:
        from ..gluon.parameter import params_swapped

        if prefill == "batched":
            def run(param_vals, q8, packed_t, prompt_dev, key0):
                with params_swapped(params, param_vals):
                    ck = jnp.zeros((NL, B, KV, total, D), cdtype)
                    cv = jnp.zeros((NL, B, KV, total, D), cdtype)
                    logits, ck, cv = prefill_batch(prompt_dev, ck, cv)
                    first = _sample(logits, P - 1, key0)

                    def scan_body(carry, t):
                        tok, ck, cv = carry
                        logits, ck, cv = (
                            fused_token(tok, t, ck, cv, packed_t, q8)
                            if use_fused
                            else one_token(tok, t, ck, cv, q8))
                        nxt = _sample(logits, t, key0)
                        return (nxt, ck, cv), nxt

                    (_, _, _), toks = lax.scan(
                        scan_body, (first, ck, cv),
                        jnp.arange(P, total - 1))
                    return jnp.concatenate([first[None], toks])  # (N, B)
        else:
            def run(param_vals, q8, packed_t, prompt_dev, key0):
                with params_swapped(params, param_vals):

                    def scan_body(carry, t):
                        tok, ck, cv = carry
                        # teacher-force while t is inside the prompt
                        cur = jnp.where(t < P,
                                        prompt_dev[:, jnp.minimum(t, P - 1)],
                                        tok)
                        logits, ck, cv = (
                            fused_token(cur, t, ck, cv, packed_t, q8)
                            if use_fused
                            else one_token(cur, t, ck, cv, q8))
                        nxt = _sample(logits, t, key0)
                        return (nxt, ck, cv), nxt

                    ck = jnp.zeros((NL, B, KV, total, D), cdtype)
                    cv = jnp.zeros((NL, B, KV, total, D), cdtype)
                    tok0 = jnp.zeros((B,), jnp.int32)
                    (_, _, _), toks = lax.scan(scan_body, (tok0, ck, cv),
                                               jnp.arange(total - 1))
                    # positions P-1 .. total-2 sampled the new tokens
                    return toks[P - 1:]                        # (N, B)

        cache[cache_key] = jax.jit(run)

    new = onp.asarray(cache[cache_key](
        param_vals, q8v, packed, jnp.asarray(prompt),
        jax.random.PRNGKey(seed))).T
    return onp.concatenate([prompt, new], axis=1)
