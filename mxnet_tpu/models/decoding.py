"""KV-cache incremental decoding for transformer-decoder models.

``GPT.generate`` recomputes the full prefix for every new token (O(L²) per
token, one jit program per prefix length — the BucketingModule analog).
``kv_generate`` is the TPU-native decoder: a fixed-shape per-layer K/V
cache updated with ``lax.dynamic_update_slice``, the WHOLE decode loop
(prefill + sampling) compiled as ONE ``lax.scan`` program — no per-token
dispatch, no retraces, O(L) work per token.

Three per-token step implementations share the program skeleton
(``decode_mode`` picks one):

- **stacked** (default where supported): every layer's weights are
  stacked into ``(NL, ...)`` arrays (``ops.decode_fused.
  stack_decode_weights``) and the per-token layer loop is ONE
  ``lax.scan`` over the layer axis — the compiled step contains one
  layer-body's worth of HLO instead of NL unrolled copies.  The r4
  profile showed the decode scan is SEQUENCER-bound (~230 device ops ×
  ~2.5 µs/step of fixed per-op cost, BASELINE.md), so collapsing the op
  count is the measured fix, and it is portable XLA — it lands on CPU CI
  as well as TPU.  Covers the ``weights="int8"`` stream too (stacked q8
  codes ride the scan xs through ``q8_matvec``), and a per-slot variant
  (``pool_token``) is the serving step of ``mxnet_tpu.serve``.
  ``MXNET_STACKED_DECODE=0`` restores the unrolled path bit-for-bit.
- **unrolled**: the r3 generalization path (VERDICT r2 item 8) — the
  per-layer math is DERIVED FROM THE MODEL'S OWN BLOCKS (``ln1``/
  ``attn.qkv``/``ffn``/… invoked as Gluon layers on traced values via
  the same swap discipline as ``SPMDTrainer``), so a model variant that
  changes normalization, activation, or bias structure inside those
  sublayers decodes correctly with no decoder change.  Only the
  cache-attention core is decoder-specific math.  This is the fallback
  for non-uniform layer stacks (and any block variant the stacked gate
  rejects), in both native and int8 weight modes.
- **fused**: the TPU Pallas megakernel (``ops/decode_fused.py``) — ALL
  layers in one kernel launch per token.  Explicit opt-in only
  (``fused="on"``): the kernel is TPU-only and narrowly gated (batch ≤ 4,
  bf16 cache, chunk-tileable dims — see PARITY.md "Decode path support
  matrix"), so the portable stacked path is the default op-count
  collapse.

Decodable protocol — two block families are recognized:
- GPT/_TransformerCell: ``wte``+``wpe`` embeddings, blocks with ``ln1``,
  ``attn`` (fused ``qkv``+``proj``), ``ln2``, ``ffn``;
- Llama: ``wte`` only (RoPE applied per step via the ``rope`` op's
  ``position_offset``), blocks with ``rms1``, ``attn`` (separate
  ``q_proj``/``k_proj``/``v_proj``/``o_proj``, grouped-query kv heads),
  ``rms2``, ``mlp``.
Final norm is ``ln_f``; the head is a ``head``/``lm_head`` Block or the
tied ``wte`` weight.

Reference counterpart: none in-tree (GluonNLP-era beam/sampling ran the
full-prefix path); this is a NEW capability like flash/ring attention.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

__all__ = ["kv_generate", "decode_mode", "decode_step_program"]

# Trace-time cross-thread serialization — one lock for every
# ``params_swapped`` site (kv_generate, the serving loop, _CachedOp);
# defined next to the swap it guards.
from ..gluon.parameter import _TRACE_LOCK


def _call(layer, *vals):
    """Invoke a Gluon (Hybrid)Block imperatively on traced jax values."""
    from ..gluon.block import _no_hybrid
    from ..ndarray.ndarray import NDArray
    from .. import autograd

    with autograd.pause(train_mode=False), _no_hybrid():
        out = layer(*[v if isinstance(v, NDArray) else NDArray(v)
                      for v in vals])
    return out._data if isinstance(out, NDArray) else out


def _quantize_rows(w):
    """Per-output-channel symmetric int8 quantization: w (out, in) →
    (int8 codes TRANSPOSED to (in, out) for the streaming kernel's
    canonical matmul layout, f32 scales (out,)).  bf16 exactly represents
    every int in [-127, 127], so the in-dot convert loses nothing;
    accumulation runs f32 via ``preferred_element_type``."""
    w32 = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(w32), axis=1) / 127.0, 1e-8)
    wq = jnp.round(w32 / s[:, None]).astype(jnp.int8)
    return wq.T.copy(), s


def _quantize_head(w, bias=None):
    """Head quantization with the vocab dim padded to the 128 lane tile:
    GPT-2's 50257 is not a lane multiple, and an unpadded head silently
    falls back to the dequantizing XLA einsum (measured 8x slower than
    bf16) for the LARGEST matmul of every decode step.  Pads codes with
    zeros and scales with 1.0 (padded logits come out 0 and are sliced
    off by the caller, which tracks the true vocab statically); returns
    (codes, scales, bias_or_None)."""
    wq, s = _quantize_rows(w)
    pad = (-wq.shape[1]) % 128
    if pad:
        wq = jnp.pad(wq, ((0, 0), (0, pad)))
        s = jnp.pad(s, (0, pad), constant_values=1.0)
        if bias is not None:
            bias = jnp.pad(bias.astype(jnp.float32), (0, pad))
    return wq, s, bias


# the int8 KV page pair's dtypes — ONE page is (codes, scale-per-
# (layer, head)).  These must agree with the serve operand schema's
# KV_PAGE_INT8 declaration (``mxnet_tpu/serve/schema.py``), which the
# page-pool pricing and ``telemetry_report --check-serve`` consume;
# tests/test_serve_schema.py pins the two equal (decoding cannot
# import serve without a cycle, so the contract is test-held).
_KV_CODE_DTYPE = jnp.int8
_KV_SCALE_DTYPE = jnp.float32


def _kv_dequant(codes, scales, dtype):
    """Int8 KV page codes -> ``dtype`` values: ``codes * scale`` with
    the per-page-per-head f32 scale broadcast over the trailing
    ``(page, D)`` axes.  A sentinel gather fills codes AND scales with
    zeros, so unmapped pages dequantize to the exact zeros the f32
    pool's fill would have produced."""
    return (codes.astype(_KV_SCALE_DTYPE)
            * scales[..., None, None]).astype(dtype)


def _kv_requant(vals, floor_scales):
    """Symmetric per-page-row int8 quantization of ``vals`` over its
    trailing ``(page, D)`` axes, with the new scale FLOORED at the
    page's previous scale (pass ``0.0`` for fresh pages).  The floor
    is what keeps the read-modify-write page rewrites lossless for
    untouched columns: when a new column does not raise the page's
    dynamic range the scale is unchanged and every existing code
    round-trips to itself exactly (``round(c * s / s) == c``) — zero
    drift over the up-to-``page`` step rewrites a frontier page sees.
    When the range DOES grow, the whole page re-rounds at the coarser
    scale, exactly what a one-shot quantization of the final page
    contents would have produced.  Two preconditions keep the ratchet
    honest (both documented in PARITY.md):

    - a page must enter a slot's reservation with a ZERO scale — the
      serving admission/chunk executables scale-reset every freshly
      allocated page (a zero scale dequantizes a recycled page's stale
      codes to exact zeros), so the floor can never inherit a previous
      tenant's dynamic range;
    - speculative verify quantizes drafted columns BEFORE acceptance
      is known, so a rejected draft's magnitude can ratchet its page's
      scale (see ``_kv_verify_rmw``) — the one case where the final
      scale may be coarser than one-shot quantization of the surviving
      contents."""
    v32 = vals.astype(_KV_SCALE_DTYPE)
    amax = jnp.max(jnp.abs(v32), axis=(-2, -1))
    s = jnp.maximum(jnp.maximum(amax / 127.0, floor_scales), 1e-8)
    codes = jnp.round(v32 / s[..., None, None]).astype(_KV_CODE_DTYPE)
    return codes, s


def _kv_step_rmw(pool, pg, iB, offs, newcol):
    """Requantizing single-column page rewrite for the paged pool STEP:
    gather each slot's frontier page ``pg[b]`` (codes + scale),
    dequantize, land slot ``b``'s new K or V column at page offset
    ``offs[b]``, re-quantize with the old scale as floor, and scatter
    codes+scales back (``mode="drop"``: a retired lane's sentinel page
    id cannot touch a freed page).  ``newcol`` is ``(B, NL, KV, D)``
    — the advanced-index layout of the dense per-slot scatter this
    replaces.  Write pages are exclusively owned (COW guarantees the
    shared prefix never holds a slot's write frontier), so the
    whole-page scatter never races another slot."""
    codes, scales = pool
    old_s = scales.at[:, pg].get(mode="fill", fill_value=0)
    vals = _kv_dequant(codes.at[:, pg].get(mode="fill", fill_value=0),
                       old_s, jnp.float32)       # (NL, B, KV, page, D)
    vals = vals.at[:, iB, :, offs, :].set(newcol.astype(jnp.float32))
    q, s = _kv_requant(vals, old_s)
    return (codes.at[:, pg].set(q, mode="drop"),
            scales.at[:, pg].set(s, mode="drop"))


def _kv_chunk_rmw(pool, wpgs, loc, new_cd, page, ntp):
    """Requantizing page-WINDOW rewrite for ``chunk_tokens``: the
    chunk's ``C`` consecutive positions touch at most ``ntp``
    consecutive pages of one slot's row.  Gather the window,
    dequantize, land the chunk columns at their window-local offsets
    ``loc`` (out-of-window entries DROP — bucket-padded tails and
    positions past the cache horizon never land), re-quantize each
    window page with its old scale as floor, scatter back.  ``new_cd``
    is ``(NL, KV, C, D)``."""
    codes, scales = pool
    old_s = scales.at[:, wpgs].get(mode="fill", fill_value=0)
    win = _kv_dequant(codes.at[:, wpgs].get(mode="fill", fill_value=0),
                      old_s, jnp.float32)        # (NL, NTP, KV, page, D)
    NL, _, KV, _, D = win.shape
    flat = jnp.moveaxis(win, 2, 1).reshape(NL, KV, ntp * page, D)
    flat = flat.at[:, :, loc, :].set(new_cd.astype(jnp.float32),
                                     mode="drop")
    win = jnp.moveaxis(flat.reshape(NL, KV, ntp, page, D), 2, 1)
    q, s = _kv_requant(win, old_s)
    return (codes.at[:, wpgs].set(q, mode="drop"),
            scales.at[:, wpgs].set(s, mode="drop"))


def _kv_verify_rmw(pool, wpgs, iB, loc, new_bd, page, ntp):
    """Requantizing per-slot page-window rewrite for
    ``pool_verify_paged``: like ``_kv_chunk_rmw`` batched over slots —
    slot ``b``'s block touches window pages ``wpgs[b]`` with
    window-local column offsets ``loc[b]``.  Slots' write windows are
    disjoint (every window page belongs to its slot's reserved,
    exclusively-owned range), so the batched whole-page scatter never
    collides.  ``new_bd`` is ``(B, C, NL, KV, D)``.

    Known deviation (documented in PARITY.md): all ``C`` drafted
    columns quantize here BEFORE acceptance is known.  Rejection rolls
    ``pos`` back — the garbage columns become unreachable and are
    overwritten by later writes at the same positions — but a rejected
    draft's magnitude has already ratcheted the page scale via the
    monotone floor, so subsequently accepted tokens on that page can
    quantize coarser than a one-shot quantization of the surviving
    contents.  Accepted-column error still respects the per-write
    ``scale/2`` code-step bound; the end-to-end effect is covered by
    the pinned greedy-agreement tolerance."""
    codes, scales = pool
    old_s = scales.at[:, wpgs].get(mode="fill", fill_value=0)
    win = _kv_dequant(codes.at[:, wpgs].get(mode="fill", fill_value=0),
                      old_s, jnp.float32)     # (NL, B, NTP, KV, page, D)
    NL, B, _, KV, _, D = win.shape
    flat = jnp.moveaxis(win, 3, 2).reshape(NL, B, KV, ntp * page, D)
    flat = flat.at[:, iB[:, None], :, loc, :].set(
        new_bd.astype(jnp.float32), mode="drop")
    win = jnp.moveaxis(flat.reshape(NL, B, KV, ntp, page, D), 3, 2)
    q, s = _kv_requant(win, old_s)
    return (codes.at[:, wpgs].set(q, mode="drop"),
            scales.at[:, wpgs].set(s, mode="drop"))


def _gpt_act_type(model):
    """fc1 activation of the first block (None for a linear fc1 — and
    for FFN variants without the fc1/act structure: the unrolled path
    calls the whole ffn Block and never needs the act type, so an
    unrecognized shape must not break the generality fallback)."""
    try:
        fc1 = model.blocks[0].ffn.fc1
        act = fc1.act
    except AttributeError:
        return None
    return getattr(act, "_act_type", None) if act is not None else None


def _check_args(prefill, weights, fused, stacked):
    """Shared argument validation — runs even on the max_new_tokens<=0
    early return so a typo fails fast in 0-token smoke calls."""
    if prefill not in ("batched", "scan"):
        raise ValueError(f"prefill must be 'batched' or 'scan', "
                         f"got {prefill!r}")
    if weights not in ("native", "int8"):
        raise ValueError(f"weights must be 'native' or 'int8', "
                         f"got {weights!r}")
    if fused not in ("auto", "on", "off"):
        raise ValueError(f"fused must be 'auto', 'on' or 'off', "
                         f"got {fused!r}")
    if stacked not in ("auto", "on", "off"):
        raise ValueError(f"stacked must be 'auto', 'on' or 'off', "
                         f"got {stacked!r}")


def _family_tables(is_llama):
    """THE per-family slot maps — projection layers and stacked norm
    params, keyed by the slot names the scan body reads.  Every consumer
    (``_layer_weight_srcs`` cache pinning, ``_build_q8`` unrolled codes,
    ``_build_q8_stacked`` scan xs) derives from these two dicts, so a
    new projection or a third block family is a one-place edit."""
    if is_llama:
        proj = {"q": lambda blk: blk.attn.q_proj,
                "k": lambda blk: blk.attn.k_proj,
                "v": lambda blk: blk.attn.v_proj,
                "o": lambda blk: blk.attn.o_proj,
                "gate": lambda blk: blk.mlp.gate,
                "up": lambda blk: blk.mlp.up,
                "down": lambda blk: blk.mlp.down}
        norms = {"rms1_g": lambda blk: blk.rms1.gamma,
                 "rms2_g": lambda blk: blk.rms2.gamma}
    else:
        proj = {"qkv": lambda blk: blk.attn.qkv,
                "proj": lambda blk: blk.attn.proj,
                "fc1": lambda blk: blk.ffn.fc1,
                "fc2": lambda blk: blk.ffn.fc2}
        norms = {"ln1_g": lambda blk: blk.ln1.gamma,
                 "ln1_b": lambda blk: blk.ln1.beta,
                 "ln2_g": lambda blk: blk.ln2.gamma,
                 "ln2_b": lambda blk: blk.ln2.beta}
    return proj, norms


def _layer_weight_srcs(model, is_llama):
    """Pinned strong refs to every per-layer weight/bias/norm array —
    the cache-invalidation key shared by the Pallas pack and the stacked
    export: a train step rebinds parameter arrays, so comparing these by
    ``is`` detects staleness without hashing (and without the recycled-
    ``id()`` hazard documented at the q8 cache)."""
    proj, norms = _family_tables(is_llama)
    srcs = []
    for blk in model.blocks:
        for get in proj.values():
            lyr = get(blk)
            srcs.append(lyr.weight.data()._data)
            if getattr(lyr, "bias", None) is not None:
                srcs.append(lyr.bias.data()._data)
        for get in norms.values():
            srcs.append(get(blk).data()._data)
    return srcs


def _pinned_cache(model, attr, srcs, build):
    """Source-pinned model cache: rebuild ``build()`` whenever any source
    array was rebound (compared by ``is`` against pinned strong refs)."""
    cache = model.__dict__.setdefault(attr, {})
    cached = cache.get("srcs")
    if cached is None or len(cached) != len(srcs) or \
            not all(a is b for a, b in zip(cached, srcs)):
        cache["srcs"] = srcs
        cache["val"] = build()
    return cache["val"]


def decode_mode(model, batch=1, total=32, weights="native", fused="auto",
                stacked="auto"):
    """Select the per-token step implementation ``kv_generate`` will run.

    Returns ``"fused"`` | ``"stacked"`` | ``"unrolled"``.

    ``fused="on"`` requires the Pallas megakernel (raises ``MXNetError``
    when its gate — TPU backend, batch ≤ 4, bf16, tileable dims —
    rejects the config); ``"auto"``/``"off"`` never select it: the
    kernel is TPU-only and shipped unmeasured (VERDICT r5), so it is
    explicit opt-in.  ``stacked="on"`` requires the stacked-layer scan
    (raises when the model is not stackable); ``"auto"`` uses it
    whenever supported — for both ``weights`` modes (the int8 stream
    stacks its q8 codes); ``"off"`` never.  The
    ``MXNET_STACKED_DECODE=0`` escape hatch disables the stacked path
    globally — with ``stacked="on"`` that conflict raises rather than
    silently overriding either request."""
    from ..base import MXNetError
    from ..ops.decode_fused import (fused_decode_supported,
                                    stacked_decode_supported)

    _check_args("batched", weights, fused, stacked)
    if fused == "on":
        if stacked == "on":
            raise MXNetError("stacked='on' conflicts with fused='on' — "
                             "the Pallas megakernel replaces the layer "
                             "loop entirely")
        cdtype = model.wte.weight.data()._data.dtype
        ok = fused_decode_supported(model._cfg, batch, total, cdtype)
        if ok and not hasattr(model.blocks[0], "rms1"):
            ok = _gpt_act_type(model) in (None, "gelu", "relu")
        if not ok:
            raise MXNetError(
                "fused='on' but the fused decode kernel does not support "
                "this model/batch/dtype (see ops/decode_fused.py "
                "fused_decode_supported)")
        return "fused"
    env_on = os.environ.get("MXNET_STACKED_DECODE", "1") != "0"
    if stacked == "on":
        if not env_on:
            raise MXNetError("stacked='on' but MXNET_STACKED_DECODE=0 "
                             "disables the stacked decode path")
        if not stacked_decode_supported(model):
            raise MXNetError(
                "stacked='on' but this model's layer stack cannot be "
                "stacked (non-uniform geometry/eps/activation or an "
                "unrecognized block family — see ops/decode_fused.py "
                "stacked_decode_supported)")
        return "stacked"
    if stacked == "auto" and env_on and stacked_decode_supported(model):
        return "stacked"
    return "unrolled"


class _DecodeEngine:
    """Per-call decode program builder: family/geometry detection, weight
    preparation (q8 codes / Pallas pack / stacked arrays — all cached on
    the model pinned to their source arrays, all riding as TRACED
    ARGUMENTS so weight updates never invalidate the compiled program),
    and the per-token step bodies the jitted ``run`` composes."""

    def __init__(self, model, B, P, total, temperature, top_k, prefill,
                 weights, fused, stacked):
        with _TRACE_LOCK:
            self._init(model, B, P, total, temperature, top_k, prefill,
                       weights, fused, stacked)

    def _init(self, model, B, P, total, temperature, top_k, prefill,
              weights, fused, stacked):
        cfg = model._cfg
        self.model = model
        self.cfg = cfg
        self.B, self.P, self.total = B, P, total
        self.temperature, self.top_k = temperature, top_k
        self.prefill = prefill
        self.H = cfg.num_heads
        self.U = cfg.units
        self.D = self.U // self.H
        # family detection (see module docstring): Llama cells carry
        # separate projections + RoPE and may use fewer kv heads (GQA)
        self.is_llama = hasattr(model.blocks[0], "rms1")
        self.KV = getattr(cfg, "num_kv_heads", self.H) if self.is_llama \
            else self.H
        self.rope_base = float(getattr(cfg, "rope_base", 10000.0))
        _check_args(prefill, weights, fused, stacked)
        self.use_int8 = weights == "int8"

        # weights ride as TRACED ARGUMENTS (swap discipline shared with
        # SPMDTrainer._forward_loss): updates to the model do NOT
        # invalidate the compiled decode program
        self.params = [p for p in model.collect_params().values()
                       if p._data is not None]
        self.param_vals = [p._data._data for p in self.params]
        self.NL = len(model.blocks)
        self.cdtype = model.wte.weight.data()._data.dtype
        self.scale = 1.0 / (self.D ** 0.5)
        self.head = getattr(model, "head", None) or \
            getattr(model, "lm_head", None)
        if self.is_llama:
            self.act_t = None
            self.norm_eps = (
                float(getattr(model.blocks[0].rms1, "_eps", 1e-6)),
                float(getattr(model.blocks[0].rms2, "_eps", 1e-6)))
        else:
            self.act_t = _gpt_act_type(model)
            self.norm_eps = (
                float(getattr(model.blocks[0].ln1, "_eps", 1e-5)),
                float(getattr(model.blocks[0].ln2, "_eps", 1e-5)))

        self.mode = decode_mode(model, B, total, weights, fused, stacked)
        self.packed = self.q8v = self.sw = None
        if self.mode == "fused":
            self.packed = self._build_packed()
        elif self.mode == "stacked":
            if self.use_int8:
                # int8 stacked: the scan streams per-layer q8 codes as
                # xs; only the LM head rides through the q8v operand
                sq8 = self._build_q8_stacked()
                self.sw = {k: v for k, v in sq8.items() if k != "head"}
                self.q8v = {"head": sq8["head"]}
                self.head_vocab = self._head_vocab()
            else:
                self.sw = _pinned_cache(
                    model, "_stacked_decode_cache",
                    _layer_weight_srcs(model, self.is_llama),
                    model.stacked_decode_weights)
        if self.use_int8 and self.q8v is None:
            self.q8v = self._build_q8()

    # -- weight preparation -------------------------------------------- #
    def _build_packed(self):
        """Pallas megakernel stream, cached pinned on the source arrays
        (a train step rebinds arrays → repack)."""
        from ..ops.decode_fused import (pack_gpt_weights,
                                        pack_llama_weights)
        model, cfg, cdtype = self.model, self.cfg, self.cdtype
        if self.is_llama:
            return _pinned_cache(
                model, "_fused_decode_cache",
                [self.use_int8] + _layer_weight_srcs(model, True),
                lambda: pack_llama_weights(model.blocks, cfg, cdtype,
                                           quant=self.use_int8))
        return _pinned_cache(
            model, "_fused_decode_cache",
            [self.use_int8] + _layer_weight_srcs(model, False),
            lambda: pack_gpt_weights(model.blocks, cdtype,
                                     quant=self.use_int8))

    def _head_arrays(self):
        """(head weight (V, U), head bias or None) — the tied ``wte``
        weight when the model has no separate head Block."""
        head = self.head
        head_w = (head.weight if head is not None
                  else self.model.wte.weight).data()._data
        head_b = None
        if head is not None and getattr(head, "bias", None) is not None:
            head_b = head.bias.data()._data
        return head_w, head_b

    def _head_vocab(self):
        return int(self._head_arrays()[0].shape[0])

    def _build_q8(self):
        """int8 weight streaming: quantize the decode matmul weights.
        Codes are cached keyed on the SOURCE ARRAYS THEMSELVES (weights
        AND biases), compared by ``is`` against pinned strong refs — a
        train step rebinds the arrays and triggers requantization, while
        repeated generate calls reuse the codes.  Pinning the sources
        (not id() snapshots) is load-bearing: freed buffer addresses get
        recycled by CPython, so an id()-keyed cache can silently serve
        stale codes after an update."""
        model = self.model
        head_w, head_b = self._head_arrays()
        self.head_vocab = int(head_w.shape[0])
        proj, _ = _family_tables(self.is_llama)
        lyr_tabs = [{k: get(blk) for k, get in proj.items()}
                    for blk in model.blocks]
        srcs = [l.weight.data()._data for t in lyr_tabs
                for l in t.values()]
        srcs += [l.bias.data()._data for t in lyr_tabs
                 for l in t.values()
                 if getattr(l, "bias", None) is not None]
        srcs.append(head_w)
        if head_b is not None:
            srcs.append(head_b)

        def _q(lyr):
            wq, s = _quantize_rows(lyr.weight.data()._data)
            b = None
            if getattr(lyr, "bias", None) is not None:
                b = lyr.bias.data()._data
            return (wq, s, b)

        return _pinned_cache(
            model, "_q8_weight_cache", srcs,
            lambda: {
                "blocks": [{k: _q(l) for k, l in t.items()}
                           for t in lyr_tabs],
                "head": _quantize_head(head_w, head_b),
            })

    def _build_q8_stacked(self):
        """int8 codes for the STACKED scan: every projection's per-layer
        (in, out) codes / (out,) scales / biases stacked to (NL, ...)
        arrays that ride the layer scan's xs, next to the stacked norm
        rows (same slot names as the native stack so the scan body
        shares its norm code).  Missing biases stack as zeros (adding
        f32 0 is exact, matching the unrolled path's no-bias add) unless
        the whole family is bias-free (Llama), where the slot is
        dropped.  Cached pinned on the layer+head source arrays — the
        same rebind-invalidation discipline as ``_build_q8``."""
        model = self.model
        head_w, head_b = self._head_arrays()
        srcs = _layer_weight_srcs(model, self.is_llama) + [head_w]
        if head_b is not None:
            srcs.append(head_b)

        def _build():
            kinds, norms = _family_tables(self.is_llama)
            out = {}
            for kind, get in kinds.items():
                qs, ss, bs = [], [], []
                any_bias = any(getattr(get(blk), "bias", None) is not None
                               for blk in model.blocks)
                for blk in model.blocks:
                    lyr = get(blk)
                    wq, s = _quantize_rows(lyr.weight.data()._data)
                    qs.append(wq)
                    ss.append(s)
                    if any_bias:
                        b = lyr.bias.data()._data \
                            if getattr(lyr, "bias", None) is not None \
                            else jnp.zeros((wq.shape[1],), self.cdtype)
                        bs.append(b)
                out[kind] = (jnp.stack(qs), jnp.stack(ss),
                             jnp.stack(bs) if any_bias else None)
            for name, get in norms.items():
                out[name] = jnp.stack(
                    [get(blk).data()._data for blk in model.blocks])
            out["head"] = _quantize_head(head_w, head_b)
            return out

        return _pinned_cache(model, "_q8_stacked_cache", srcs, _build)

    # -- step bodies ---------------------------------------------------- #
    def _dense_q8(self, x, ent, act_type=None):
        """Weight-only int8 matvec via the Pallas streaming kernel: int8
        codes convert to bf16 IN VMEM (exact for |code| ≤ 127), f32 MXU
        accumulation, per-channel rescale."""
        from ..ops.q8_matvec import q8_matvec
        from ..ops.registry import get_op
        wq, s, b = ent
        y = q8_matvec(x, wq, s, b).astype(self.cdtype)
        if act_type:
            y = get_op("Activation").fn(y, act_type=act_type)
        return y

    def _sample_logits(self, logits):
        """Shared temperature/top_k logits preparation — ``None`` means
        greedy (argmax).  The batch sampler and the serving per-slot
        sampler (``serve.engine.PoolPrograms._sample_slots``) both draw
        from THIS prep, so a sampler tweak (e.g. top_p) lands in the
        offline and served streams together — the parity contract."""
        temperature, top_k = self.temperature, self.top_k
        if temperature == 0.0:
            return None
        # temperature is a python-scalar closure capture, not an operand:
        # the cast folds at trace time (no suppression needed — the jit
        # seeds here close over the engine, so this is host-side prep)
        lg = logits / max(float(temperature), 1e-6)
        if top_k and top_k < lg.shape[-1]:
            kth = jax.lax.top_k(lg, top_k)[0][:, -1]
            lg = jnp.where(lg < kth[:, None], -jnp.inf, lg)
        return lg

    def _sample(self, logits, t, key0):
        lg = self._sample_logits(logits)
        if lg is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            jax.random.fold_in(key0, t), lg, axis=-1).astype(jnp.int32)

    def _head_logits(self, xl, q8):
        """ln_f output (B, U) → f32 logits (B, V); shared by every step
        body and the batched prefill tail."""
        model, head = self.model, self.head
        if q8 is not None:
            from ..ops.q8_matvec import q8_matvec
            hwq, hs, hb = q8["head"]
            # slice the 128-padded vocab back down; the true vocab is a
            # STATIC closure value (an int in the traced pytree would
            # arrive as a tracer and break the slice)
            return q8_matvec(xl, hwq, hs, hb)[:, :self.head_vocab]
        if head is not None:
            return _call(head, xl).astype(jnp.float32)
        w = model.wte.weight.data()._data                 # traced (swap)
        return (xl @ w.T).astype(jnp.float32)

    def _embed(self, x_tok, pos):
        x = _call(self.model.wte, x_tok)
        if not self.is_llama:
            x = x + _call(self.model.wpe,
                          jnp.broadcast_to(pos, (self.B,)))
        return x

    def one_token(self, x_tok, pos, ck, cv, q8=None):
        """x_tok (B,) int32 at position pos -> (logits (B,V), new caches).
        ck/cv: (NL, B, KV, maxT, D).  All layer math comes from the
        model's own sublayers; only the cached-attention core (and RoPE
        application for Llama) is inlined — the generality fallback (and
        the int8 path): decodes any block variant, at NL unrolled copies
        of the layer body in the compiled step."""
        from ..ops.attention import rope as _rope

        model = self.model
        B, U, H, KV, D = self.B, self.U, self.H, self.KV, self.D
        is_llama, cdtype = self.is_llama, self.cdtype

        x = self._embed(x_tok, pos)
        idx = lax.broadcasted_iota(jnp.int32, (1, 1, self.total), 2)
        for i, blk in enumerate(model.blocks):
            # one copy of the projection math for both weight modes
            def _lin(layer, kind, h):
                return self._dense_q8(h, q8["blocks"][i][kind]) \
                    if q8 is not None else _call(layer, h)

            if is_llama:
                h = _call(blk.rms1, x)
                q = _lin(blk.attn.q_proj, "q", h).reshape(B, H, 1, D)
                k = _lin(blk.attn.k_proj, "k", h).reshape(B, KV, 1, D)
                v = _lin(blk.attn.v_proj, "v", h).reshape(B, KV, 1, D)
                q = _rope.__wrapped__(q, base=self.rope_base,
                                      position_offset=pos)
                k = _rope.__wrapped__(k, base=self.rope_base,
                                      position_offset=pos)
            else:
                h = _call(blk.ln1, x)
                qkv = self._dense_q8(h, q8["blocks"][i]["qkv"]) \
                    if q8 is not None \
                    else _call(blk.attn.qkv, h)               # (B, 3U)
                q, k, v = (qkv[:, j * U:(j + 1) * U].reshape(B, H, 1, D)
                           for j in range(3))
            ck = lax.dynamic_update_slice(ck, k[None], (i, 0, 0, pos, 0))
            cv = lax.dynamic_update_slice(cv, v[None], (i, 0, 0, pos, 0))
            kc, vc = ck[i], cv[i]                             # (B,KV,T,D)
            # grouped einsums contract q's head groups directly against
            # the KV-head cache — no materialized H-head repeat (the GQA
            # memory-bandwidth benefit is the point of the small cache)
            qg = q.reshape(B, KV, H // KV, D)
            s = jnp.einsum("bkgd,bktd->bkgt", qg, kc,
                           preferred_element_type=jnp.float32) * self.scale
            s = jnp.where(idx[:, :, None] <= pos, s, -1e30)   # (B,KV,G,T)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bkgt,bktd->bkgd", p, vc).reshape(B, U)
            if is_llama:
                x = x + _lin(blk.attn.o_proj, "o", o)
                h2 = _call(blk.rms2, x)
                if q8 is not None:
                    # SwiGLU decomposed: down(silu(gate)·up), matching
                    # models/llama.py (the native arm calls the whole
                    # mlp Block so model variants keep working)
                    g = _lin(blk.mlp.gate, "gate", h2)
                    u = _lin(blk.mlp.up, "up", h2)
                    x = x + _lin(blk.mlp.down, "down",
                                 g * jax.nn.sigmoid(g) * u)
                else:
                    x = x + _call(blk.mlp, h2)
            elif q8 is not None:
                x = x + self._dense_q8(o, q8["blocks"][i]["proj"])
                h2 = _call(blk.ln2, x)
                x = x + self._dense_q8(
                    self._dense_q8(h2, q8["blocks"][i]["fc1"],
                                   self.act_t),
                    q8["blocks"][i]["fc2"])
            else:
                x = x + _call(blk.attn.proj, o)
                x = x + _call(blk.ffn, _call(blk.ln2, x))
        xl = _call(model.ln_f, x)
        return self._head_logits(xl, q8), ck, cv

    def stacked_token(self, x_tok, pos, ck, cv, sw, q8=None):
        """one_token's stacked twin — THE op-count collapse: the layer
        loop is ONE ``lax.scan`` over the (NL, ...) stacked weights
        (``sw``), with the per-layer K/V cache slices riding the scan's
        xs and the two new cache columns coming back as ys (written into
        the carried caches with ONE dynamic_update_slice each).  The
        body dispatches the IDENTICAL op functions the model's sublayers
        dispatch (FullyConnected / LayerNorm / RMSNorm / Activation /
        rope, same arguments), so greedy and sampled token streams match
        the unrolled path.  With ``weights='int8'`` the xs carry stacked
        q8 codes/scales instead and every projection runs ``q8_matvec``
        (the same kernel and cast order as the unrolled q8 path, so int8
        stacked matches int8 unrolled token-for-token).  Compiled cost:
        one layer-body of HLO + the embed/head/sample tail, ~5x under
        the unrolled step's op count at GPT-2-small depth
        (benchmark/decode_bench.py ops/step)."""
        return self._scan_token(x_tok, pos, ck, cv, sw, q8,
                                per_slot=False)

    def pool_token(self, x_tok, pos, ck, cv, sw, q8=None):
        """stacked_token with PER-ROW positions — the slot-pool serving
        step (``mxnet_tpu.serve``): every batch row is an independent
        sequence at its own depth ``pos[b]``, so the attention mask,
        rotary angles and cache-column writes are per-slot (the writes
        are scatters at ``(b, pos[b])`` instead of one
        dynamic_update_slice).  Retired slots keep computing (masked by
        the caller) — their cache writes land at their stale position
        and are overwritten on admission, so no branch, no retrace, no
        host sync."""
        return self._scan_token(x_tok, pos, ck, cv, sw, q8,
                                per_slot=True)

    def pool_token_paged(self, x_tok, pos, kp, vp, pt, page, sw, q8=None):
        """pool_token against a PAGED pool (``mxnet_tpu.serve``): the
        caches are page pools ``(NL, NPAGES, KV, page, D)`` and each
        slot reads/writes them through its page-table row ``pt[b]``
        (``pt``: (B, MAXP) int32, a TRACED operand — allocation churn
        changes table VALUES, never shapes, so no retrace).  Rows of
        retired/idle slots hold the one-past-the-end sentinel
        ``NPAGES``: their gathers fill zeros and their scatters DROP,
        which is what makes masked zombie lanes safe — a freed page can
        never be corrupted by a slot that no longer owns it.  Token
        order is ``t = j * page + o`` (page-major), so the gathered
        dense view reproduces ``pool_token``'s attention bit-for-bit."""
        return self._scan_token(x_tok, pos, kp, vp, sw, q8,
                                per_slot=True, pages=(pt, page))

    def _scan_token(self, x_tok, pos, ck, cv, sw, q8, per_slot,
                    pages=None):
        from ..ops.attention import rope as _rope
        from ..ops.registry import get_op

        _fc = get_op("FullyConnected").fn
        _ln = get_op("LayerNorm").fn
        _rms = get_op("RMSNorm").fn
        _act = get_op("Activation").fn
        B, U, H, KV, D = self.B, self.U, self.H, self.KV, self.D
        llama, cdtype = self.is_llama, self.cdtype
        int8 = self.use_int8
        eps1, eps2 = self.norm_eps
        act_t, scale, rope_base = self.act_t, self.scale, self.rope_base
        # the unrolled q8 path's matvec+cast+activation body, verbatim —
        # stacked int8 matches unrolled int8 token-for-token through it
        _q8l = self._dense_q8

        def _ropeq(t):
            # pos is a traced scalar (stacked) or (B,) per-slot vector
            # (pool) — rope's position_offset handles both, so the pool
            # rows share the batch path's rotary math exactly
            return _rope.__wrapped__(t, base=rope_base,
                                     position_offset=pos)

        x = self._embed(x_tok, pos)
        idx = lax.broadcasted_iota(jnp.int32, (1, 1, self.total), 2)
        # (1,1,1,T) <= scalar pos, or <= (B,1,1,1) per-slot positions
        pos_b = pos[:, None, None, None] if per_slot else pos
        iB = jnp.arange(B)
        if pages is not None:
            pt, page = pages
            maxp = self.total // page
            # int8 pools ride as (codes, scales) tuples — a STATIC
            # python structure, so the branch is resolved at trace time
            # and costs the f32 path nothing
            quant = isinstance(ck, tuple)

            def _paged_view(pool_l):
                # (NPAGES, KV, page, D) pool layer -> (B, KV, T, D)
                # per-slot dense views through the page table; sentinel
                # entries (pt == NPAGES) gather zeros.  int8 pools
                # dequantize in the SAME gather (per-page scales ride
                # the scan xs next to the codes).
                if quant:
                    cdl, scl = pool_l
                    g = _kv_dequant(
                        cdl.at[pt].get(mode="fill", fill_value=0),
                        scl.at[pt].get(mode="fill", fill_value=0),
                        cdtype)
                else:
                    g = pool_l.at[pt].get(mode="fill", fill_value=0)
                return jnp.moveaxis(g, 2, 1).reshape(B, KV, self.total,
                                                     D)

        def body(x, xs):
            w, kc, vc = xs                    # per-layer slices
            if pages is not None:
                kc = _paged_view(kc)
                vc = _paged_view(vc)
            if llama:
                h = _rms(x, w["rms1_g"], eps=eps1)
                if int8:
                    q = _q8l(h, w["q"]).reshape(B, H, 1, D)
                    k = _q8l(h, w["k"]).reshape(B, KV, 1, D)
                    v = _q8l(h, w["v"]).reshape(B, KV, 1, D)
                else:
                    q = _fc(h, w["q_w"], None, no_bias=True,
                            flatten=False).reshape(B, H, 1, D)
                    k = _fc(h, w["k_w"], None, no_bias=True,
                            flatten=False).reshape(B, KV, 1, D)
                    v = _fc(h, w["v_w"], None, no_bias=True,
                            flatten=False).reshape(B, KV, 1, D)
                q = _ropeq(q)
                k = _ropeq(k)
            else:
                h = _ln(x, w["ln1_g"], w["ln1_b"], eps=eps1)
                qkv = _q8l(h, w["qkv"]) if int8 else \
                    _fc(h, w["qkv_w"], w["qkv_b"], flatten=False)
                q, k, v = (qkv[:, j * U:(j + 1) * U].reshape(B, H, 1, D)
                           for j in range(3))
            if per_slot:
                kc = kc.at[iB, :, pos, :].set(k[:, :, 0, :])
                vc = vc.at[iB, :, pos, :].set(v[:, :, 0, :])
            else:
                kc = lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
                vc = lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))
            qg = q.reshape(B, KV, H // KV, D)
            s = jnp.einsum("bkgd,bktd->bkgt", qg, kc,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(idx[:, :, None] <= pos_b, s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bkgt,bktd->bkgd", p, vc).reshape(B, U)
            if llama:
                x = x + (_q8l(o, w["o"]) if int8 else
                         _fc(o, w["o_w"], None, no_bias=True,
                             flatten=False))
                h2 = _rms(x, w["rms2_g"], eps=eps2)
                if int8:
                    g = _q8l(h2, w["gate"])
                    u = _q8l(h2, w["up"])
                    x = x + _q8l(g * jax.nn.sigmoid(g) * u, w["down"])
                else:
                    g = _fc(h2, w["gate_w"], None, no_bias=True,
                            flatten=False)
                    u = _fc(h2, w["up_w"], None, no_bias=True,
                            flatten=False)
                    x = x + _fc(g * jax.nn.sigmoid(g) * u, w["down_w"],
                                None, no_bias=True, flatten=False)
            elif int8:
                x = x + _q8l(o, w["proj"])
                h2 = _ln(x, w["ln2_g"], w["ln2_b"], eps=eps2)
                x = x + _q8l(_q8l(h2, w["fc1"], act_t), w["fc2"])
            else:
                x = x + _fc(o, w["proj_w"], w["proj_b"], flatten=False)
                h2 = _ln(x, w["ln2_g"], w["ln2_b"], eps=eps2)
                hh = _fc(h2, w["fc1_w"], w["fc1_b"], flatten=False)
                if act_t is not None:
                    hh = _act(hh, act_type=act_t)
                x = x + _fc(hh, w["fc2_w"], w["fc2_b"], flatten=False)
            return x, (k, v)

        x, (knew, vnew) = lax.scan(body, x, (sw, ck, cv))
        # knew/vnew: (NL, B, KV, 1, D) — all layers' new columns land in
        # the carried caches as ONE update (slice, or per-slot scatter)
        if pages is not None:
            # slot b's position pos[b] lives at (page pt[b, pos//page],
            # offset pos % page).  Retired slots carry the sentinel in
            # their table rows so the scatter DROPS their zombie writes;
            # the clip keeps a stale pos == T from indexing past the
            # table (it would otherwise clamp onto a live entry).
            pg = pt[iB, jnp.minimum(pos // page, maxp - 1)]
            newk = jnp.moveaxis(knew[:, :, :, 0, :], 0, 1)
            newv = jnp.moveaxis(vnew[:, :, :, 0, :], 0, 1)
            if quant:
                # requantizing page RMW: dequantize the frontier page,
                # land the column, re-quantize (old scale as floor)
                ck = _kv_step_rmw(ck, pg, iB, pos % page, newk)
                cv = _kv_step_rmw(cv, pg, iB, pos % page, newv)
            else:
                ck = ck.at[:, pg, :, pos % page, :].set(newk,
                                                        mode="drop")
                cv = cv.at[:, pg, :, pos % page, :].set(newv,
                                                        mode="drop")
        elif per_slot:
            ck = ck.at[:, iB, :, pos, :].set(
                jnp.moveaxis(knew[:, :, :, 0, :], 0, 1))
            cv = cv.at[:, iB, :, pos, :].set(
                jnp.moveaxis(vnew[:, :, :, 0, :], 0, 1))
        else:
            ck = lax.dynamic_update_slice(ck, knew, (0, 0, 0, pos, 0))
            cv = lax.dynamic_update_slice(cv, vnew, (0, 0, 0, pos, 0))
        xl = _call(self.model.ln_f, x)
        return self._head_logits(xl, q8), ck, cv

    def chunk_tokens(self, toks, off, nlast, ptrow, page, kp, vp, sw,
                     q8=None):
        """ONE CHUNK of a single sequence's prefill against the PAGED
        pool (chunked prefill and prefix-cache suffix fill,
        ``mxnet_tpu.serve``): ``toks`` (C,) int32 occupy absolute
        positions ``off .. off+C-1`` of the slot whose page-table row
        is ``ptrow`` (MAXP,) int32.  The already-cached prefix is
        gathered through the row, the chunk attends causally over
        prefix + itself (scores masked at ``t <= off + j`` — the same
        mask/softmax/einsum discipline as the decode step), chunk K/V
        scatters back through the row (positions past the reserved
        pages resolve to the sentinel and DROP), and the logits at
        absolute position ``off + nlast`` come back for the final
        chunk's first-token sample.  ``off``/``nlast`` ride as TRACED
        scalars, so one compiled program per chunk length C serves
        every landing offset — chunked admission never retraces on
        prompt length."""
        from ..ops.attention import rope as _rope
        from ..ops.registry import get_op

        _fc = get_op("FullyConnected").fn
        _ln = get_op("LayerNorm").fn
        _rms = get_op("RMSNorm").fn
        _act = get_op("Activation").fn
        U, H, KV, D = self.U, self.H, self.KV, self.D
        T = self.total
        llama, cdtype = self.is_llama, self.cdtype
        int8 = self.use_int8
        eps1, eps2 = self.norm_eps
        act_t, scale, rope_base = self.act_t, self.scale, self.rope_base
        _q8l = self._dense_q8
        C = toks.shape[0]
        G = H // KV
        maxp = T // page
        quant = isinstance(kp, tuple)      # int8 (codes, scales) pools
        npages = (kp[0] if quant else kp).shape[1]
        cpos = off + jnp.arange(C, dtype=jnp.int32)       # absolute

        x = _call(self.model.wte, toks)[None]             # (1, C, U)
        if not llama:
            x = x + _call(self.model.wpe, cpos)[None]
        # (C, T) causal mask over absolute positions: chunk row j sees
        # cached tokens 0..off+j (its own column included post-update)
        mask = jnp.arange(T, dtype=jnp.int32)[None, :] <= cpos[:, None]

        def body(x, xs):
            w, kpl, vpl = xs
            # dense (1, KV, T, D) views of this slot's cached prefix,
            # gathered through its page-table row (sentinel -> zeros;
            # int8 pools dequantize in the same gather)
            if quant:
                kpl = _kv_dequant(
                    kpl[0].at[ptrow].get(mode="fill", fill_value=0),
                    kpl[1].at[ptrow].get(mode="fill", fill_value=0),
                    cdtype)
                vpl = _kv_dequant(
                    vpl[0].at[ptrow].get(mode="fill", fill_value=0),
                    vpl[1].at[ptrow].get(mode="fill", fill_value=0),
                    cdtype)
            else:
                kpl = kpl.at[ptrow].get(mode="fill", fill_value=0)
                vpl = vpl.at[ptrow].get(mode="fill", fill_value=0)
            kc = jnp.moveaxis(kpl, 1, 0).reshape(KV, T, D)[None]
            vc = jnp.moveaxis(vpl, 1, 0).reshape(KV, T, D)[None]
            if llama:
                h = _rms(x, w["rms1_g"], eps=eps1)
                if int8:
                    # q8_matvec is strictly 2-D: project the (C, U) rows
                    q = _q8l(h[0], w["q"]).reshape(1, C, H, D)
                    k = _q8l(h[0], w["k"]).reshape(1, C, KV, D)
                    v = _q8l(h[0], w["v"]).reshape(1, C, KV, D)
                else:
                    q = _fc(h, w["q_w"], None, no_bias=True,
                            flatten=False).reshape(1, C, H, D)
                    k = _fc(h, w["k_w"], None, no_bias=True,
                            flatten=False).reshape(1, C, KV, D)
                    v = _fc(h, w["v_w"], None, no_bias=True,
                            flatten=False).reshape(1, C, KV, D)
                q = q.transpose(0, 2, 1, 3)               # (1, H, C, D)
                k = k.transpose(0, 2, 1, 3)
                v = v.transpose(0, 2, 1, 3)
                q = _rope.__wrapped__(q, base=rope_base,
                                      position_offset=off)
                k = _rope.__wrapped__(k, base=rope_base,
                                      position_offset=off)
            else:
                h = _ln(x, w["ln1_g"], w["ln1_b"], eps=eps1)
                qkv = _q8l(h[0], w["qkv"])[None] if int8 else \
                    _fc(h, w["qkv_w"], w["qkv_b"], flatten=False)
                q, k, v = (qkv[..., j * U:(j + 1) * U]
                           .reshape(1, C, H, D).transpose(0, 2, 1, 3)
                           for j in range(3))
            k = k.astype(cdtype)
            v = v.astype(cdtype)
            # chunk K/V lands in the dense view BEFORE attention, so
            # one mask covers prefix and intra-chunk causality together
            kc = lax.dynamic_update_slice(kc, k, (0, 0, off, 0))
            vc = lax.dynamic_update_slice(vc, v, (0, 0, off, 0))
            qg = q.reshape(1, KV, G, C, D)
            s = jnp.einsum("bkgcd,bktd->bkgct", qg, kc,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask[None, None, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bkgct,bktd->bkgcd", p, vc)
            o = o.transpose(0, 3, 1, 2, 4).reshape(1, C, U)
            if llama:
                x = x + (_q8l(o[0], w["o"])[None] if int8 else
                         _fc(o, w["o_w"], None, no_bias=True,
                             flatten=False))
                h2 = _rms(x, w["rms2_g"], eps=eps2)
                if int8:
                    g = _q8l(h2[0], w["gate"])
                    u = _q8l(h2[0], w["up"])
                    x = x + _q8l(g * jax.nn.sigmoid(g) * u,
                                 w["down"])[None]
                else:
                    g = _fc(h2, w["gate_w"], None, no_bias=True,
                            flatten=False)
                    u = _fc(h2, w["up_w"], None, no_bias=True,
                            flatten=False)
                    x = x + _fc(g * jax.nn.sigmoid(g) * u, w["down_w"],
                                None, no_bias=True, flatten=False)
            elif int8:
                x = x + _q8l(o[0], w["proj"])[None]
                h2 = _ln(x, w["ln2_g"], w["ln2_b"], eps=eps2)
                x = x + _q8l(_q8l(h2[0], w["fc1"], act_t),
                             w["fc2"])[None]
            else:
                x = x + _fc(o, w["proj_w"], w["proj_b"], flatten=False)
                h2 = _ln(x, w["ln2_g"], w["ln2_b"], eps=eps2)
                hh = _fc(h2, w["fc1_w"], w["fc1_b"], flatten=False)
                if act_t is not None:
                    hh = _act(hh, act_type=act_t)
                x = x + _fc(hh, w["fc2_w"], w["fc2_b"], flatten=False)
            return x, (k, v)

        x, (knew, vnew) = lax.scan(body, x, (sw, kp, vp))
        # knew/vnew: (NL, 1, KV, C, D) — scatter every chunk column
        # through the page-table row.  Positions past the reserved
        # pages (bucket-padded tails) resolve to the sentinel and DROP;
        # the explicit cpos < T guard covers tails that would otherwise
        # CLIP onto the row's own last page and corrupt earlier tokens.
        if quant:
            # requantizing page-WINDOW RMW: the C consecutive columns
            # touch at most ntp consecutive pages of this row (static
            # in C and page, so the program shape is unchanged).  Pad
            # columns past ``nlast`` are masked OUT here — unlike the
            # f32 path's harmless garbage-but-unreachable writes, a pad
            # column would poison its page's shared SCALE.
            ntp = (C + page - 2) // page + 1
            p0 = off // page
            widx = p0 + jnp.arange(ntp, dtype=jnp.int32)
            wpgs = jnp.where(widx < maxp,
                             ptrow[jnp.minimum(widx, maxp - 1)],
                             npages)                       # (NTP,)
            keepc = (jnp.arange(C, dtype=jnp.int32) <= nlast) & \
                (cpos < T)
            loc = jnp.where(keepc, cpos - p0 * page, ntp * page)
            kp = _kv_chunk_rmw(kp, wpgs, loc, knew[:, 0], page, ntp)
            vp = _kv_chunk_rmw(vp, wpgs, loc, vnew[:, 0], page, ntp)
        else:
            pgs = jnp.where(cpos < T,
                            ptrow[jnp.minimum(cpos // page, maxp - 1)],
                            npages)                        # (C,)
            offs = cpos % page
            kp = kp.at[:, pgs, :, offs, :].set(
                jnp.moveaxis(knew[:, 0], 2, 0), mode="drop")
            vp = vp.at[:, pgs, :, offs, :].set(
                jnp.moveaxis(vnew[:, 0], 2, 0), mode="drop")
        x_last = lax.dynamic_slice(x, (0, nlast, 0), (1, 1, U))[:, 0]
        xl = _call(self.model.ln_f, x_last)
        # the chunk head is native, matching prefill_batch (q8 covers
        # the per-token decode matvecs; each chunk runs once)
        return self._head_logits(xl, None), kp, vp

    def pool_verify_paged(self, toks, pos, pt, page, kp, vp, sw,
                          q8=None):
        """Draft-and-verify scoring against the PAGED pool
        (``mxnet_tpu.serve`` speculative decoding): every slot ``b``
        carries a block ``toks[b]`` (C,) int32 whose column 0 is the
        slot's last emitted token (already sampled, not yet attended)
        and columns 1..C-1 are host-drafted candidates, occupying
        absolute positions ``pos[b] .. pos[b]+C-1``.  ONE dispatch
        computes the model's next-token logits at ALL C positions —
        ``out[b, j]`` is the token the plain step path would have
        produced after attending position ``pos[b]+j`` — so the caller
        accepts the longest prefix where ``out[:, :-1]`` matches the
        drafts.  The block's K/V columns scatter through the page
        table like ``chunk_tokens``; a rejected tail needs NO undo:
        its columns sit past the slot's advanced length, hidden by the
        causal mask and overwritten (write-before-attend) by the next
        dispatch that reaches those positions, and pages are reserved
        for the full ``prompt+max_new`` budget at admission, so
        rollback never moves a refcount.  Structurally this is
        ``chunk_tokens`` batched over slots — per-row positions ride
        as a traced (B,) operand (one compiled program per block
        width C, zero retraces under accept/reject churn), the same
        mask/softmax/einsum discipline, the same sentinel-row DROP
        semantics for retired lanes — crossed with ``_scan_token``'s
        per-slot paged views and q8 head (the parity contract: a
        verify column's logits come from the same projections and
        head as the plain step's)."""
        from ..ops.attention import rope as _rope
        from ..ops.registry import get_op

        _fc = get_op("FullyConnected").fn
        _ln = get_op("LayerNorm").fn
        _rms = get_op("RMSNorm").fn
        _act = get_op("Activation").fn
        B, U, H, KV, D = self.B, self.U, self.H, self.KV, self.D
        T = self.total
        llama, cdtype = self.is_llama, self.cdtype
        int8 = self.use_int8
        eps1, eps2 = self.norm_eps
        act_t, scale, rope_base = self.act_t, self.scale, self.rope_base
        _q8l = self._dense_q8
        C = toks.shape[1]
        G = H // KV
        maxp = T // page
        quant = isinstance(kp, tuple)      # int8 (codes, scales) pools
        npages = (kp[0] if quant else kp).shape[1]
        iB = jnp.arange(B)
        cpos = pos[:, None] + jnp.arange(C, dtype=jnp.int32)   # (B, C)
        # dense-view write positions: a column past the cache horizon
        # (a near-budget slot co-resident with a deeper block, or a
        # zombie lane's stale pos) aims one-past-the-end and DROPS —
        # clamping instead would overwrite the slot's own live T-1
        # column before attention.  Such columns are never accepted
        # (the verify program caps advance at the slot's stop).
        wpos = jnp.where(cpos < T, cpos, T)

        x = _call(self.model.wte, toks)                    # (B, C, U)
        if not llama:
            x = x + _call(self.model.wpe, cpos)
        # (B, C, T) causal mask over absolute positions: block column j
        # of slot b sees cached tokens 0..pos[b]+j (itself included
        # post-update) — a rejected earlier burst's stale columns sit
        # PAST pos[b]+j and stay masked out
        mask = jnp.arange(T, dtype=jnp.int32)[None, None, :] <= \
            cpos[:, :, None]

        def body(x, xs):
            w, kpl, vpl = xs
            # per-slot dense (B, KV, T, D) views through the page
            # table; sentinel rows (retired slots) gather zeros (int8
            # pools dequantize in the same gather)
            if quant:
                kpl = _kv_dequant(
                    kpl[0].at[pt].get(mode="fill", fill_value=0),
                    kpl[1].at[pt].get(mode="fill", fill_value=0),
                    cdtype)
                vpl = _kv_dequant(
                    vpl[0].at[pt].get(mode="fill", fill_value=0),
                    vpl[1].at[pt].get(mode="fill", fill_value=0),
                    cdtype)
            else:
                kpl = kpl.at[pt].get(mode="fill", fill_value=0)
                vpl = vpl.at[pt].get(mode="fill", fill_value=0)
            kc = jnp.moveaxis(kpl, 2, 1).reshape(B, KV, T, D)
            vc = jnp.moveaxis(vpl, 2, 1).reshape(B, KV, T, D)
            if llama:
                h = _rms(x, w["rms1_g"], eps=eps1)
                if int8:
                    # q8_matvec is strictly 2-D: project (B*C, U) rows
                    h2d = h.reshape(B * C, U)
                    q = _q8l(h2d, w["q"]).reshape(B, C, H, D)
                    k = _q8l(h2d, w["k"]).reshape(B, C, KV, D)
                    v = _q8l(h2d, w["v"]).reshape(B, C, KV, D)
                else:
                    q = _fc(h, w["q_w"], None, no_bias=True,
                            flatten=False).reshape(B, C, H, D)
                    k = _fc(h, w["k_w"], None, no_bias=True,
                            flatten=False).reshape(B, C, KV, D)
                    v = _fc(h, w["v_w"], None, no_bias=True,
                            flatten=False).reshape(B, C, KV, D)
                q = q.transpose(0, 2, 1, 3)               # (B, H, C, D)
                k = k.transpose(0, 2, 1, 3)
                v = v.transpose(0, 2, 1, 3)
                # per-slot rotary phase: rope broadcasts a (B,) offset
                # to per-row absolute positions pos[b] + j
                q = _rope.__wrapped__(q, base=rope_base,
                                      position_offset=pos)
                k = _rope.__wrapped__(k, base=rope_base,
                                      position_offset=pos)
            else:
                h = _ln(x, w["ln1_g"], w["ln1_b"], eps=eps1)
                qkv = _q8l(h.reshape(B * C, U),
                           w["qkv"]).reshape(B, C, 3 * U) if int8 \
                    else _fc(h, w["qkv_w"], w["qkv_b"], flatten=False)
                q, k, v = (qkv[..., j * U:(j + 1) * U]
                           .reshape(B, C, H, D).transpose(0, 2, 1, 3)
                           for j in range(3))
            k = k.astype(cdtype)
            v = v.astype(cdtype)
            # block K/V lands in the dense views BEFORE attention
            # (per-slot scatter — offsets vary per row), so one mask
            # covers cached prefix and intra-block causality together
            kc = kc.at[iB[:, None], :, wpos].set(
                k.transpose(0, 2, 1, 3), mode="drop")
            vc = vc.at[iB[:, None], :, wpos].set(
                v.transpose(0, 2, 1, 3), mode="drop")
            qg = q.reshape(B, KV, G, C, D)
            s = jnp.einsum("bkgcd,bktd->bkgct", qg, kc,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask[:, None, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bkgct,bktd->bkgcd", p, vc)
            o = o.transpose(0, 3, 1, 2, 4).reshape(B, C, U)
            if llama:
                x = x + (_q8l(o.reshape(B * C, U),
                              w["o"]).reshape(B, C, U) if int8 else
                         _fc(o, w["o_w"], None, no_bias=True,
                             flatten=False))
                h2 = _rms(x, w["rms2_g"], eps=eps2)
                if int8:
                    h2d = h2.reshape(B * C, U)
                    g = _q8l(h2d, w["gate"])
                    u = _q8l(h2d, w["up"])
                    x = x + _q8l(g * jax.nn.sigmoid(g) * u,
                                 w["down"]).reshape(B, C, U)
                else:
                    g = _fc(h2, w["gate_w"], None, no_bias=True,
                            flatten=False)
                    u = _fc(h2, w["up_w"], None, no_bias=True,
                            flatten=False)
                    x = x + _fc(g * jax.nn.sigmoid(g) * u, w["down_w"],
                                None, no_bias=True, flatten=False)
            elif int8:
                x = x + _q8l(o.reshape(B * C, U),
                             w["proj"]).reshape(B, C, U)
                h2 = _ln(x, w["ln2_g"], w["ln2_b"], eps=eps2)
                x = x + _q8l(_q8l(h2.reshape(B * C, U), w["fc1"],
                                  act_t), w["fc2"]).reshape(B, C, U)
            else:
                x = x + _fc(o, w["proj_w"], w["proj_b"], flatten=False)
                h2 = _ln(x, w["ln2_g"], w["ln2_b"], eps=eps2)
                hh = _fc(h2, w["fc1_w"], w["fc1_b"], flatten=False)
                if act_t is not None:
                    hh = _act(hh, act_type=act_t)
                x = x + _fc(hh, w["fc2_w"], w["fc2_b"], flatten=False)
            return x, (k, v)

        x, (knew, vnew) = lax.scan(body, x, (sw, kp, vp))
        # knew/vnew: (NL, B, KV, C, D) — scatter every block column of
        # every slot through its page-table row.  Out-of-range columns
        # (zombie lanes past T) resolve to the sentinel and DROP; the
        # cpos < T guard keeps them from CLIPPING onto a live page.
        if quant:
            # per-slot requantizing page-window RMW (the chunk write
            # batched over slots): slot b's C columns touch at most ntp
            # consecutive pages from its frontier page pos[b] // page
            ntp = (C + page - 2) // page + 1
            p0 = pos // page                               # (B,)
            widx = p0[:, None] + jnp.arange(ntp, dtype=jnp.int32)
            wpgs = jnp.where(widx < maxp,
                             pt[iB[:, None],
                                jnp.minimum(widx, maxp - 1)],
                             npages)                       # (B, NTP)
            loc = jnp.where(cpos < T, cpos - p0[:, None] * page,
                            ntp * page)                    # (B, C)
            kp = _kv_verify_rmw(kp, wpgs, iB, loc,
                                jnp.transpose(knew, (1, 3, 0, 2, 4)),
                                page, ntp)
            vp = _kv_verify_rmw(vp, wpgs, iB, loc,
                                jnp.transpose(vnew, (1, 3, 0, 2, 4)),
                                page, ntp)
        else:
            pgs = jnp.where(cpos < T,
                            pt[iB[:, None], jnp.minimum(cpos // page,
                                                        maxp - 1)],
                            npages)                        # (B, C)
            offs = cpos % page
            # result dims of the non-adjacent advanced indices go
            # FIRST: value shape (B, C, NL, KV, D)
            kp = kp.at[:, pgs, :, offs, :].set(
                jnp.transpose(knew, (1, 3, 0, 2, 4)), mode="drop")
            vp = vp.at[:, pgs, :, offs, :].set(
                jnp.transpose(vnew, (1, 3, 0, 2, 4)), mode="drop")
        xl = _call(self.model.ln_f, x)
        # same head as the plain step (q8 when int8) — the greedy
        # parity contract: out[b, 0]'s logits == the step path's
        logits = self._head_logits(xl.reshape(B * C, U), q8)
        return logits.reshape(B, C, -1), kp, vp

    def fused_token(self, x_tok, pos, ck, cv, packed_t, q8=None):
        """one_token's Pallas twin: embeddings and head stay XLA ops;
        every transformer layer runs inside ONE Pallas kernel
        (ops/decode_fused.py decode_step).  In int8 mode the layer
        stream is int8 codes and the head goes through q8_matvec, same
        as the unfused q8 path."""
        from ..ops.decode_fused import decode_step

        x = self._embed(x_tok, pos)
        x, ck, cv = decode_step(pos, x, packed_t, ck, cv, self.cfg,
                                self.act_t, self.norm_eps[0])
        xl = _call(self.model.ln_f, x)
        return self._head_logits(xl, q8), ck, cv

    def token_step(self, tok, t, ck, cv, q8, packed_t, sw):
        """Dispatch one per-token step through the selected mode."""
        if self.mode == "fused":
            return self.fused_token(tok, t, ck, cv, packed_t, q8)
        if self.mode == "stacked":
            return self.stacked_token(tok, t, ck, cv, sw, q8)
        return self.one_token(tok, t, ck, cv, q8)

    def prefill_batch(self, prompt_dev, ck, cv, last_index=None):
        """One causal forward over the whole (B, P) prompt: fills cache
        positions [0, P) and returns the position-P-1 logits (or the
        position-``last_index`` logits when given — the serving
        admission path right-pads prompts to a compiled bucket length
        and reads the logits at the true last token; the padded tail's
        cache columns are overwritten by decode steps before any step
        attends to them).  ``last_index`` may be a scalar (every row
        ends at the same position) or a per-row ``(B,)`` vector — the
        RAGGED-ROW case batched admission dispatches: each row is an
        independent right-padded prompt with its own true length, and
        its logits are gathered at its own last real token.  Because
        every row starts at position 0, the rows share one causal mask
        and one rope phase (``position_offset=0``); a row's padding
        positions attend only backward into its own real tokens, and
        their outputs are never read — per-row raggedness surfaces
        only in the last-index gather here and in the caller's masked
        cache scatter.  Exact same math as the per-token path
        (einsum + f32 softmax), reshaped onto MXU-friendly (B·P, ·)
        GEMMs."""
        from ..ops.attention import rope as _rope

        from ..ops.registry import get_op
        _flash_fn = get_op("flash_attention").fn

        model = self.model
        B, P = self.B, self.P
        U, H, KV, D = self.U, self.H, self.KV, self.D
        is_llama, cdtype = self.is_llama, self.cdtype

        x = _call(model.wte, prompt_dev)                      # (B, P, U)
        if not is_llama:
            pos = jnp.arange(P, dtype=jnp.int32)
            x = x + _call(model.wpe, jnp.broadcast_to(pos[None], (B, P)))
        for i, blk in enumerate(model.blocks):
            if is_llama:
                h = _call(blk.rms1, x)
                q = _call(blk.attn.q_proj, h).reshape(
                    B, P, H, D).transpose(0, 2, 1, 3)
                k = _call(blk.attn.k_proj, h).reshape(
                    B, P, KV, D).transpose(0, 2, 1, 3)
                v = _call(blk.attn.v_proj, h).reshape(
                    B, P, KV, D).transpose(0, 2, 1, 3)
                q = _rope.__wrapped__(q, base=self.rope_base,
                                      position_offset=0)
                k = _rope.__wrapped__(k, base=self.rope_base,
                                      position_offset=0)
            else:
                h = _call(blk.ln1, x)
                qkv = _call(blk.attn.qkv, h)                  # (B, P, 3U)
                q, k, v = (qkv[..., j * U:(j + 1) * U]
                           .reshape(B, P, H, D).transpose(0, 2, 1, 3)
                           for j in range(3))
            ck = lax.dynamic_update_slice(
                ck, k.astype(cdtype)[None], (i, 0, 0, 0, 0))
            cv = lax.dynamic_update_slice(
                cv, v.astype(cdtype)[None], (i, 0, 0, 0, 0))
            # causal attention over the prompt via the flash kernel —
            # O(P) memory (no (P, P) score tensor), so long prompts
            # prefill without OOM; GQA repeats k/v across head groups
            kf, vf = k, v
            if KV != H:
                kf = jnp.repeat(k, H // KV, axis=1)
                vf = jnp.repeat(v, H // KV, axis=1)
            o = _flash_fn(q, kf, vf, None, scale=self.scale, causal=True)
            o = o.transpose(0, 2, 1, 3).reshape(B, P, U)
            if is_llama:
                x = x + _call(blk.attn.o_proj, o)
                x = x + _call(blk.mlp, _call(blk.rms2, x))
            else:
                x = x + _call(blk.attn.proj, o)
                x = x + _call(blk.ffn, _call(blk.ln2, x))
        if last_index is None:
            x_last = x[:, -1]
        else:
            li = jnp.asarray(last_index)
            if li.ndim == 0:
                x_last = lax.dynamic_index_in_dim(x, li, axis=1,
                                                  keepdims=False)
            else:
                # ragged rows: gather row b's hidden state at its own
                # last real token li[b]
                x_last = jnp.take_along_axis(
                    x, li.astype(jnp.int32)[:, None, None],
                    axis=1)[:, 0]
        xl = _call(model.ln_f, x_last)
        # the prefill head is always native (q8 covers decode-step
        # matvecs; the prefill runs once)
        return self._head_logits(xl, None), ck, cv

    def zero_caches(self):
        shape = (self.NL, self.B, self.KV, self.total, self.D)
        return jnp.zeros(shape, self.cdtype), \
            jnp.zeros(shape, self.cdtype)

    def cache_bytes(self):
        """Device bytes of the K/V cache pair this engine's programs
        carry — the dominant in-executable allocation, reported as the
        ``cache_bytes`` field on the decode sites' compile events so a
        recording can split "KV cache" from "everything else" inside
        ``mem_temp_bytes`` without re-deriving the geometry."""
        return 2 * self.NL * self.B * self.KV * self.total * self.D \
            * jnp.dtype(self.cdtype).itemsize

    def take_operands(self):
        """Hand the weight operands (param values + prepared q8/packed/
        stacked arrays) to the caller and DROP the engine's own refs:
        the compiled program closure keeps the engine alive, and it must
        not pin the first call's arrays after a train-step rebind."""
        operands = (self.param_vals, self.q8v, self.packed, self.sw)
        self.param_vals = self.q8v = self.packed = self.sw = None
        return operands

    def build_run(self):
        """The whole-decode program (prefill + sampled scan) to be
        jitted: run(param_vals, q8, packed_t, sw, prompt_dev, key0) →
        (N, B) new tokens."""
        from ..gluon.parameter import params_swapped

        eng = self
        P, total = self.P, self.total

        if self.prefill == "batched":
            def run(param_vals, q8, packed_t, sw, prompt_dev, key0):
                with _TRACE_LOCK, params_swapped(eng.params, param_vals):
                    ck, cv = eng.zero_caches()
                    logits, ck, cv = eng.prefill_batch(prompt_dev, ck, cv)
                    first = eng._sample(logits, P - 1, key0)

                    def scan_body(carry, t):
                        tok, ck, cv = carry
                        logits, ck, cv = eng.token_step(
                            tok, t, ck, cv, q8, packed_t, sw)
                        nxt = eng._sample(logits, t, key0)
                        return (nxt, ck, cv), nxt

                    (_, _, _), toks = lax.scan(
                        scan_body, (first, ck, cv),
                        jnp.arange(P, total - 1))
                    return jnp.concatenate([first[None], toks])  # (N, B)
        else:
            def run(param_vals, q8, packed_t, sw, prompt_dev, key0):
                with _TRACE_LOCK, params_swapped(eng.params, param_vals):

                    def scan_body(carry, t):
                        tok, ck, cv = carry
                        # teacher-force while t is inside the prompt
                        cur = jnp.where(t < P,
                                        prompt_dev[:, jnp.minimum(t, P - 1)],
                                        tok)
                        logits, ck, cv = eng.token_step(
                            cur, t, ck, cv, q8, packed_t, sw)
                        nxt = eng._sample(logits, t, key0)
                        return (nxt, ck, cv), nxt

                    ck, cv = eng.zero_caches()
                    tok0 = jnp.zeros((eng.B,), jnp.int32)
                    (_, _, _), toks = lax.scan(scan_body, (tok0, ck, cv),
                                               jnp.arange(total - 1))
                    # positions P-1 .. total-2 sampled the new tokens
                    return toks[P - 1:]                        # (N, B)

        return run


def kv_generate(model, prompt_tokens, max_new_tokens=32, temperature=1.0,
                top_k=0, seed=0, prefill="batched", weights="native",
                fused="auto", stacked="auto"):
    """Sample ``max_new_tokens`` continuations for a (B, P) prompt.

    Greedy when ``temperature == 0``; ``top_k > 0`` restricts the sample
    space (sampling uses ``jax.random.categorical`` with a per-step
    ``fold_in(key, t)`` key — deterministic given ``seed``).  Matches
    ``model.generate`` token-for-token in greedy mode (the KV-cached
    attention is mathematically identical to full recompute).  Returns
    the full (B, P + max_new_tokens) int32 array.

    ``prefill``: ``"batched"`` (default) runs the whole prompt through
    ONE causal forward that fills the K/V cache — P-1 sequential scan
    steps collapse into one MXU-shaped pass; ``"scan"`` keeps the
    token-at-a-time prefill (same token stream either way — the sampling
    key at position t is ``fold_in(key, t)`` in both modes).

    ``weights``: ``"int8"`` streams the decode-step matmul weights as
    per-channel-quantized int8 (half the HBM bytes of bf16),
    dequantizing inside the dot with f32 accumulation.  Both families
    (GPT fused-QKV and Llama split-projection/SwiGLU).  An approximate
    path — greedy tokens can differ from the exact native path (~0.4%
    weight error); measured r4: the decode step is sequencer-bound at
    GPT-2-small size, so int8's byte savings pay off only on larger
    models (BASELINE.md decode section).  int8 runs the stacked-layer
    scan wherever the native path does (stacked q8 codes ride the scan
    xs; see PARITY.md decode support matrix), falling back to the
    per-layer unrolled step like native weights.

    ``stacked``: ``"auto"`` (default) runs the decode scan step as ONE
    ``lax.scan`` over stacked (NL, ...) layer weights whenever the model
    qualifies (uniform GPT or Llama/GQA layer stack, native weights) —
    the compiled step carries one layer-body's worth of HLO instead of
    NL copies, collapsing the measured ~230-op/step sequencer overhead
    (BASELINE.md r4) on ANY backend; ``"on"`` requires it (raises if
    unsupported); ``"off"`` keeps the per-layer unrolled step.
    ``MXNET_STACKED_DECODE=0`` restores the unrolled path bit-for-bit.

    ``fused``: ``"on"`` runs the decode scan step through the
    one-kernel-per-token Pallas megakernel (ops/decode_fused.py),
    raising if its gate rejects the config (TPU backend, batch ≤ 4,
    bf16 cache, chunk-tileable dims — PARITY.md support matrix).
    ``"auto"``/``"off"`` never select it: the kernel is TPU-only and
    unmeasured (VERDICT r5), so since the stacked-scan landing it is
    explicit opt-in only.  Hidden states can differ from the unfused
    path by ~1 bf16 ulp (chunked f32 accumulation order in fc2) —
    greedy token parity is asserted in tests on the covered sizes.
    """
    _check_args(prefill, weights, fused, stacked)
    prompt = onp.asarray(
        prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
        else prompt_tokens, dtype=onp.int32)
    B, P = prompt.shape
    if max_new_tokens <= 0:
        return prompt.copy()
    total = P + max_new_tokens
    if total > model._cfg.max_length:
        raise ValueError(f"prompt+new = {total} exceeds max_length "
                         f"{model._cfg.max_length}")

    eng = _DecodeEngine(model, B, P, total, temperature, top_k, prefill,
                        weights, fused, stacked)
    cache_key = (B, P, max_new_tokens, float(temperature), int(top_k),
                 str(eng.cdtype), prefill, weights, eng.mode)
    cache = model.__dict__.setdefault("_kv_decode_cache", {})
    if cache_key not in cache:
        from .. import telemetry
        cache[cache_key] = telemetry.instrument_jit(
            jax.jit(eng.build_run()), "models.kv_generate",
            key=cache_key, fields={"mode": eng.mode, "batch": B,
                                   "prompt_len": P,
                                   "new_tokens": max_new_tokens,
                                   "cache_bytes": eng.cache_bytes()})

    # the weight operands must not stay pinned on the engine: the cached
    # jitted run closes over it for the model's lifetime, and a train
    # step rebinds the parameter arrays — a retained first-call copy
    # would be a leaked full weight set per cache entry (the per-model
    # _pinned_cache entries are the intended reuse point; they are
    # REPLACED on rebind, freeing the old arrays)
    operands = eng.take_operands()
    new = onp.asarray(cache[cache_key](
        *operands, jnp.asarray(prompt), jax.random.PRNGKey(seed))).T
    return onp.concatenate([prompt, new], axis=1)


def decode_step_program(model, batch=1, total=32, temperature=0.0,
                        top_k=0, weights="native", fused="auto",
                        stacked="auto", seed=0):
    """ONE decode step as a ``(jitted_fn, example_args)`` pair — the unit
    ``profiler_xla.hlo_op_count`` measures and the op-count regression
    test / ``benchmark/decode_bench.py`` ops/step column assert on.

    ``fn(param_vals, q8, packed_t, sw, tok, pos, ck, cv, key0)`` →
    ``(next_tok (B,), ck, cv)`` for a token at position ``pos`` against
    a ``total``-slot cache; the weight operands in ``example_args`` are
    the same traced-argument set the full ``kv_generate`` program uses,
    so the counted HLO is the per-step slice of the real decode scan."""
    eng = _DecodeEngine(model, batch, max(total - 1, 1), total,
                        temperature, top_k, "batched", weights, fused,
                        stacked)
    from ..gluon.parameter import params_swapped

    def step(param_vals, q8, packed_t, sw, tok, pos, ck, cv, key0):
        with _TRACE_LOCK, params_swapped(eng.params, param_vals):
            logits, ck, cv = eng.token_step(tok, pos, ck, cv, q8,
                                            packed_t, sw)
            nxt = eng._sample(logits, pos, key0)
        return nxt, ck, cv

    ck, cv = eng.zero_caches()
    # same closure-pinning discipline as kv_generate: the returned fn
    # closes over the engine, so the caller-owned args tuple holds the
    # only weight refs
    args = (*eng.take_operands(),
            jnp.zeros((batch,), jnp.int32),
            jnp.asarray(max(total - 2, 0), jnp.int32), ck, cv,
            jax.random.PRNGKey(seed))
    from .. import telemetry
    fn = telemetry.instrument_jit(
        jax.jit(step), "models.decode_step",
        key=(batch, total, weights, eng.mode),
        fields={"mode": eng.mode, "batch": batch,
                "cache_bytes": eng.cache_bytes()})
    return fn, args
