"""KV-cache incremental decoding for transformer-decoder models.

``GPT.generate`` recomputes the full prefix for every new token (O(L²) per
token, one jit program per prefix length — the BucketingModule analog).
``kv_generate`` is the TPU-native decoder: a fixed-shape per-layer K/V
cache updated with ``lax.dynamic_update_slice``, the WHOLE decode loop
(prefill + sampling) compiled as ONE ``lax.scan`` program — no per-token
dispatch, no retraces, O(L) work per token.

r3 generalization (VERDICT r2 item 8): the per-layer math is DERIVED FROM
THE MODEL'S OWN BLOCKS — ``ln1``/``attn.qkv``/``attn.proj``/``ln2``/
``ffn``/``ln_f`` are invoked as Gluon layers on traced values (weights are
traced arguments via the same swap discipline as ``SPMDTrainer``), so a
model variant that changes normalization, activation, or bias structure
inside those sublayers decodes correctly with no decoder change.  Only the
cache-attention core (one-token query against the running K/V cache) is
decoder-specific math.

Decodable protocol — two block families are recognized:
- GPT/_TransformerCell: ``wte``+``wpe`` embeddings, blocks with ``ln1``,
  ``attn`` (fused ``qkv``+``proj``), ``ln2``, ``ffn``;
- Llama: ``wte`` only (RoPE applied per step via the ``rope`` op's
  ``position_offset``), blocks with ``rms1``, ``attn`` (separate
  ``q_proj``/``k_proj``/``v_proj``/``o_proj``, grouped-query kv heads),
  ``rms2``, ``mlp``.
Final norm is ``ln_f``; the head is a ``head``/``lm_head`` Block or the
tied ``wte`` weight.  In all cases the norm/projection/FFN math comes
from the model's OWN sublayers.

Reference counterpart: none in-tree (GluonNLP-era beam/sampling ran the
full-prefix path); this is a NEW capability like flash/ring attention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

__all__ = ["kv_generate"]


def _call(layer, *vals):
    """Invoke a Gluon (Hybrid)Block imperatively on traced jax values."""
    from ..gluon.block import _no_hybrid
    from ..ndarray.ndarray import NDArray
    from .. import autograd

    with autograd.pause(train_mode=False), _no_hybrid():
        out = layer(*[v if isinstance(v, NDArray) else NDArray(v)
                      for v in vals])
    return out._data if isinstance(out, NDArray) else out


def kv_generate(model, prompt_tokens, max_new_tokens=32, temperature=1.0,
                top_k=0, seed=0):
    """Sample ``max_new_tokens`` continuations for a (B, P) prompt.

    Greedy when ``temperature == 0``; ``top_k > 0`` restricts the sample
    space (sampling uses ``jax.random.categorical`` with a per-step
    ``fold_in(key, t)`` key — deterministic given ``seed``).  Matches
    ``model.generate`` token-for-token in greedy mode (the KV-cached
    attention is mathematically identical to full recompute).  Returns
    the full (B, P + max_new_tokens) int32 array.
    """
    cfg = model._cfg
    H = cfg.num_heads
    U = cfg.units
    D = U // H
    # family detection (see module docstring): Llama cells carry separate
    # projections + RoPE and may use fewer kv heads (GQA)
    is_llama = hasattr(model.blocks[0], "rms1")
    KV = getattr(cfg, "num_kv_heads", H) if is_llama else H
    rope_base = float(getattr(cfg, "rope_base", 10000.0))
    prompt = onp.asarray(
        prompt_tokens.asnumpy() if hasattr(prompt_tokens, "asnumpy")
        else prompt_tokens, dtype=onp.int32)
    B, P = prompt.shape
    total = P + max_new_tokens
    if total > cfg.max_length:
        raise ValueError(f"prompt+new = {total} exceeds max_length "
                         f"{cfg.max_length}")

    # weights ride as TRACED ARGUMENTS (swap discipline shared with
    # SPMDTrainer._forward_loss): updates to the model do NOT invalidate
    # the compiled decode program
    params = [p for p in model.collect_params().values()
              if p._data is not None]
    param_vals = [p._data._data for p in params]
    NL = len(model.blocks)
    cdtype = model.wte.weight.data()._data.dtype
    scale = 1.0 / (D ** 0.5)
    head = getattr(model, "head", None) or getattr(model, "lm_head", None)

    cache_key = (B, P, max_new_tokens, float(temperature), int(top_k),
                 str(cdtype))
    cache = model.__dict__.setdefault("_kv_decode_cache", {})

    def one_token(x_tok, pos, ck, cv):
        """x_tok (B,) int32 at position pos -> (logits (B,V), new caches).
        ck/cv: (NL, B, KV, maxT, D).  All layer math comes from the
        model's own sublayers; only the cached-attention core (and RoPE
        application for Llama) is inlined."""
        from ..ops.attention import rope as _rope

        x = _call(model.wte, x_tok)
        if not is_llama:
            x = x + _call(model.wpe, jnp.broadcast_to(pos, (B,)))
        idx = lax.broadcasted_iota(jnp.int32, (1, 1, total), 2)
        for i, blk in enumerate(model.blocks):
            if is_llama:
                h = _call(blk.rms1, x)
                q = _call(blk.attn.q_proj, h).reshape(B, H, 1, D)
                k = _call(blk.attn.k_proj, h).reshape(B, KV, 1, D)
                v = _call(blk.attn.v_proj, h).reshape(B, KV, 1, D)
                q = _rope.__wrapped__(q, base=rope_base,
                                      position_offset=pos)
                k = _rope.__wrapped__(k, base=rope_base,
                                      position_offset=pos)
            else:
                h = _call(blk.ln1, x)
                qkv = _call(blk.attn.qkv, h)                  # (B, 3U)
                q, k, v = (qkv[:, j * U:(j + 1) * U].reshape(B, H, 1, D)
                           for j in range(3))
            ck = lax.dynamic_update_slice(ck, k[None], (i, 0, 0, pos, 0))
            cv = lax.dynamic_update_slice(cv, v[None], (i, 0, 0, pos, 0))
            kc, vc = ck[i], cv[i]                             # (B,KV,T,D)
            # grouped einsums contract q's head groups directly against
            # the KV-head cache — no materialized H-head repeat (the GQA
            # memory-bandwidth benefit is the point of the small cache)
            qg = q.reshape(B, KV, H // KV, D)
            s = jnp.einsum("bkgd,bktd->bkgt", qg, kc,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(idx[:, :, None] <= pos, s, -1e30)   # (B,KV,G,T)
            p = jax.nn.softmax(s, axis=-1).astype(cdtype)
            o = jnp.einsum("bkgt,bktd->bkgd", p, vc).reshape(B, U)
            if is_llama:
                x = x + _call(blk.attn.o_proj, o)
                x = x + _call(blk.mlp, _call(blk.rms2, x))
            else:
                x = x + _call(blk.attn.proj, o)
                x = x + _call(blk.ffn, _call(blk.ln2, x))
        x = _call(model.ln_f, x)
        if head is not None:
            logits = _call(head, x).astype(jnp.float32)
        else:  # tied-embedding head
            w = model.wte.weight.data()._data                 # traced (swap)
            logits = (x @ w.T).astype(jnp.float32)
        return logits, ck, cv

    if cache_key not in cache:
        def run(param_vals, prompt_dev, key0):
            from ..gluon.parameter import params_swapped
            with params_swapped(params, param_vals):

                def scan_body(carry, t):
                    tok, ck, cv = carry
                    # teacher-force while t is inside the prompt
                    cur = jnp.where(t < P,
                                    prompt_dev[:, jnp.minimum(t, P - 1)],
                                    tok)
                    logits, ck, cv = one_token(cur, t, ck, cv)
                    if temperature == 0.0:
                        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    else:
                        lg = logits / max(float(temperature), 1e-6)
                        if top_k and top_k < lg.shape[-1]:
                            kth = jax.lax.top_k(lg, top_k)[0][:, -1]
                            lg = jnp.where(lg < kth[:, None], -jnp.inf, lg)
                        nxt = jax.random.categorical(
                            jax.random.fold_in(key0, t), lg,
                            axis=-1).astype(jnp.int32)
                    return (nxt, ck, cv), nxt

                ck = jnp.zeros((NL, B, KV, total, D), cdtype)
                cv = jnp.zeros((NL, B, KV, total, D), cdtype)
                tok0 = jnp.zeros((B,), jnp.int32)
                (_, _, _), toks = lax.scan(scan_body, (tok0, ck, cv),
                                           jnp.arange(total - 1))
                return toks                                    # (T-1, B)

        cache[cache_key] = jax.jit(run)

    toks = onp.asarray(cache[cache_key](
        param_vals, jnp.asarray(prompt), jax.random.PRNGKey(seed))).T
    # positions P-1 .. total-2 sampled the new tokens
    new = toks[:, P - 1:]
    return onp.concatenate([prompt, new], axis=1)
