"""Transformer seq2seq — full encoder-decoder (SURVEY.md §6 config 4
"Transformer seq2seq"; the reference serves this via GluonNLP's
``nlp.model.transformer``).

TPU-native: all attention goes through the flash kernel; the whole model
is hybridizable into one XLA program; greedy decode runs length-static
steps (compiler-friendly — no dynamic shapes inside jit).
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon.nn import Dense, Dropout, Embedding, LayerNorm
from .transformer import MultiHeadAttention, PositionwiseFFN

__all__ = ["CrossAttention", "Seq2SeqEncoder", "Seq2SeqDecoderCell",
           "Seq2SeqDecoder", "TransformerSeq2Seq"]


class CrossAttention(HybridBlock):
    """Decoder→encoder attention: queries from x, keys/values from memory."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise MXNetError(f"units {units} % heads {num_heads} != 0")
        self._units = units
        self._heads = num_heads
        self._attn_dropout = dropout
        with self.name_scope():
            self.q = Dense(units, flatten=False, use_bias=use_bias,
                           in_units=units, dtype=dtype, prefix="q_")
            self.kv = Dense(2 * units, flatten=False, use_bias=use_bias,
                            in_units=units, dtype=dtype, prefix="kv_")
            self.proj = Dense(units, flatten=False, use_bias=use_bias,
                              in_units=units, dtype=dtype, prefix="out_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        B, Lq, U = x.shape
        Lk = memory.shape[1]
        H, D = self._heads, self._units // self._heads
        q = F.transpose(F.reshape(self.q(x), shape=(B, Lq, H, D)),
                        axes=(0, 2, 1, 3))                    # (B,H,Lq,D)
        kv = F.reshape(self.kv(memory), shape=(B, Lk, 2, H, D))
        kv = F.transpose(kv, axes=(2, 0, 3, 1, 4))            # (2,B,H,Lk,D)
        k = F.reshape(F.slice_axis(kv, axis=0, begin=0, end=1),
                      shape=(B, H, Lk, D))
        v = F.reshape(F.slice_axis(kv, axis=0, begin=1, end=2),
                      shape=(B, H, Lk, D))
        out = F.flash_attention(q, k, v, mem_mask, causal=False,
                                dropout=self._attn_dropout)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)), shape=(B, Lq, U))
        out = self.proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out


class Seq2SeqDecoderCell(HybridBlock):
    """Pre-norm decoder layer: causal self-attn + cross-attn + FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units, prefix="ln1_")
            self.self_attn = MultiHeadAttention(units, num_heads, dropout,
                                                causal=True, dtype=dtype,
                                                prefix="self_")
            self.ln2 = LayerNorm(in_channels=units, prefix="ln2_")
            self.cross_attn = CrossAttention(units, num_heads, dropout,
                                             dtype=dtype, prefix="cross_")
            self.ln3 = LayerNorm(in_channels=units, prefix="ln3_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       dtype=dtype, prefix="ffn_")

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        x = x + self.self_attn(self.ln1(x))
        x = x + self.cross_attn(self.ln2(x), memory, mem_mask)
        return x + self.ffn(self.ln3(x))


class _EmbeddingStack(HybridBlock):
    def __init__(self, vocab_size, units, max_length, dropout, dtype,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.word = Embedding(vocab_size, units, dtype=dtype,
                                  prefix="word_")
            self.pos = Embedding(max_length, units, dtype=dtype,
                                 prefix="pos_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, tokens):
        B, L = tokens.shape
        pos = F.arange(L).reshape((1, L))
        x = self.word(tokens) * (self._units ** 0.5) + self.pos(pos)
        if self.drop is not None:
            x = self.drop(x)
        return x


class Seq2SeqEncoder(HybridBlock):
    def __init__(self, vocab_size, units, hidden_size, num_heads, num_layers,
                 max_length=512, dropout=0.0, dtype="float32", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from .transformer import TransformerEncoderCell
        with self.name_scope():
            self.embed = _EmbeddingStack(vocab_size, units, max_length,
                                         dropout, dtype, prefix="emb_")
            self.layers = []
            for i in range(num_layers):
                cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                              dropout, dtype=dtype,
                                              prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.layers.append(cell)
            self.ln = LayerNorm(in_channels=units, prefix="ln_")

    def hybrid_forward(self, F, src_tokens, src_mask=None):
        x = self.embed(src_tokens)
        for cell in self.layers:
            x = cell(x, src_mask) if src_mask is not None else cell(x)
        return self.ln(x)


class Seq2SeqDecoder(HybridBlock):
    def __init__(self, vocab_size, units, hidden_size, num_heads, num_layers,
                 max_length=512, dropout=0.0, dtype="float32", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.embed = _EmbeddingStack(vocab_size, units, max_length,
                                         dropout, dtype, prefix="emb_")
            self.layers = []
            for i in range(num_layers):
                cell = Seq2SeqDecoderCell(units, hidden_size, num_heads,
                                          dropout, dtype=dtype,
                                          prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.layers.append(cell)
            self.ln = LayerNorm(in_channels=units, prefix="ln_")

    def hybrid_forward(self, F, tgt_tokens, memory, mem_mask=None):
        x = self.embed(tgt_tokens)
        for cell in self.layers:
            x = cell(x, memory, mem_mask)
        return self.ln(x)


class TransformerSeq2Seq(HybridBlock):
    """Full encoder-decoder with a tied-or-free output projection.

    forward(src, tgt) → (B, L_tgt, vocab) logits (teacher forcing);
    ``greedy_decode(src, max_len, bos, eos)`` runs inference.
    """

    def __init__(self, vocab_size, units=512, hidden_size=2048, num_heads=8,
                 num_enc_layers=6, num_dec_layers=6, max_length=512,
                 dropout=0.1, tie_embeddings=True, dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._tie = tie_embeddings
        with self.name_scope():
            self.encoder = Seq2SeqEncoder(vocab_size, units, hidden_size,
                                          num_heads, num_enc_layers,
                                          max_length, dropout, dtype,
                                          prefix="enc_")
            self.decoder = Seq2SeqDecoder(vocab_size, units, hidden_size,
                                          num_heads, num_dec_layers,
                                          max_length, dropout, dtype,
                                          prefix="dec_")
            if not tie_embeddings:
                self.out_proj = Dense(vocab_size, flatten=False,
                                      in_units=units, use_bias=False,
                                      dtype=dtype, prefix="outproj_")

    def _project(self, F, x):
        if self._tie:
            w = self.decoder.embed.word.weight.data()
            return F.FullyConnected(x, w, None, num_hidden=w.shape[0],
                                    no_bias=True, flatten=False)
        return self.out_proj(x)

    def hybrid_forward(self, F, src_tokens, tgt_tokens, src_mask=None):
        memory = self.encoder(src_tokens, src_mask)
        dec = self.decoder(tgt_tokens, memory, src_mask)
        return self._project(F, dec)

    def greedy_decode(self, src_tokens, max_len=32, bos=1, eos=2):
        """Host-driven greedy decoding (clear, allocation-free per step);
        each step re-runs the decoder on the growing prefix — jit caches
        one program per prefix length like the reference's BucketingModule
        caches per-bucket graphs."""
        from .. import ndarray as nd
        import numpy as np
        B = src_tokens.shape[0]
        memory = self.encoder(src_tokens)
        out = np.full((B, 1), bos, dtype=np.int32)
        finished = np.zeros(B, dtype=bool)
        for _ in range(max_len - 1):
            tgt = nd.array(out, dtype="int32")
            dec = self.decoder(tgt, memory)
            from .. import ndarray as F
            logits = self._project(F, dec)
            nxt = onp.asarray(logits.asnumpy()[:, -1].argmax(-1),
                              dtype=np.int32)
            nxt = np.where(finished, eos, nxt)
            out = np.concatenate([out, nxt[:, None]], axis=1)
            finished |= (nxt == eos)
            if finished.all():
                break
        return out
