"""BERT encoder models (BASELINE config 3: BERT-base pretrain).

Reference counterpart: GluonNLP BERT over the contrib interleaved
self-attention ops (SURVEY.md §3.1 contrib).  TPU-native: flash-attention
encoder cells with an additive padding-mask bias, token-type + position
embeddings, pooler, and MLM/NSP heads.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..gluon.block import HybridBlock
from ..gluon.nn.basic_layers import Dense, Dropout, Embedding, LayerNorm
from .transformer import TransformerEncoderCell

__all__ = ["BERTConfig", "BERTModel", "bert_base", "bert_large"]


@dataclass
class BERTConfig:
    vocab_size: int = 30522
    max_length: int = 512
    type_vocab_size: int = 2
    num_layers: int = 12
    units: int = 768
    num_heads: int = 12
    hidden_size: int = 3072
    dropout: float = 0.0
    dtype: str = "float32"


class BERTModel(HybridBlock):
    """tokens (B, L) [+ token_types (B, L), + valid_length (B,)] →
    (sequence_output (B, L, U), pooled_output (B, U), mlm_logits)."""

    def __init__(self, config: BERTConfig, use_pooler=True, use_mlm=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._cfg = config
        self._use_pooler = use_pooler
        self._use_mlm = use_mlm
        c = config
        with self.name_scope():
            self.word_embed = Embedding(c.vocab_size, c.units,
                                        dtype=c.dtype, prefix="word_")
            self.token_type_embed = Embedding(c.type_vocab_size, c.units,
                                              dtype=c.dtype, prefix="type_")
            self.position_embed = Embedding(c.max_length, c.units,
                                            dtype=c.dtype, prefix="pos_")
            self.embed_ln = LayerNorm(in_channels=c.units, prefix="embln_")
            self.embed_drop = Dropout(c.dropout) if c.dropout else None
            self.cells = []
            for i in range(c.num_layers):
                cell = TransformerEncoderCell(
                    c.units, c.hidden_size, c.num_heads, c.dropout,
                    dtype=c.dtype,
                    prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.cells.append(cell)
            if use_pooler:
                self.pooler = Dense(c.units, flatten=False,
                                    in_units=c.units, activation="tanh",
                                    dtype=c.dtype, prefix="pooler_")
            if use_mlm:
                self.mlm_dense = Dense(c.units, flatten=False,
                                       in_units=c.units, activation="gelu",
                                       dtype=c.dtype, prefix="mlmd_")
                self.mlm_ln = LayerNorm(in_channels=c.units,
                                        prefix="mlmln_")

    def forward(self, tokens, token_types=None, valid_length=None,
                *args, **kwargs):
        from .. import ndarray as F
        B, L = tokens.shape
        c = self._cfg
        x = self.word_embed(tokens)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        pos_ids = F.broadcast_to(
            F.reshape(F.arange(L, dtype="int32"), shape=(1, L)),
            shape=(B, L))
        x = x + self.position_embed(pos_ids)
        x = self.embed_ln(x)
        if self.embed_drop is not None:
            x = self.embed_drop(x)
        mask = None
        if valid_length is not None:
            # additive key-side padding mask: (B, 1, 1, L), −1e30 at pads
            kpos = F.reshape(F.arange(L, dtype="float32"), shape=(1, 1, 1, L))
            vl = F.reshape(valid_length.astype("float32"), shape=(B, 1, 1, 1))
            mask = (F.broadcast_to(kpos, shape=(B, 1, 1, L)) >=
                    F.broadcast_to(vl, shape=(B, 1, 1, L))) * -1e30
        for cell in self.cells:
            x = cell(x) if mask is None else cell(x, mask)
        outs = [x]
        if self._use_pooler:
            cls = F.reshape(F.slice_axis(x, axis=1, begin=0, end=1), shape=(B, c.units))
            outs.append(self.pooler(cls))
        if self._use_mlm:
            h = self.mlm_ln(self.mlm_dense(x))
            w = self.word_embed.weight.data()          # tied decoder
            logits = F.dot(F.reshape(h, shape=(B * L, c.units)), w,
                           transpose_b=True)
            outs.append(F.reshape(logits, shape=(B, L, c.vocab_size)))
        return outs if len(outs) > 1 else outs[0]


def _preset(**kw):
    def make(use_pooler=True, use_mlm=True, **overrides):
        cfg = BERTConfig(**{**kw, **overrides})
        return BERTModel(cfg, use_pooler=use_pooler, use_mlm=use_mlm), cfg
    return make


bert_base = _preset(num_layers=12, units=768, num_heads=12,
                    hidden_size=3072)
bert_large = _preset(num_layers=24, units=1024, num_heads=16,
                     hidden_size=4096)
