"""Transformer building blocks (hybridizable, MXU-shaped).

Reference counterpart: GluonNLP's BERT/Transformer blocks built on the
contrib interleaved self-attention ops
(``_contrib_interleaved_matmul_selfatt_qk``, SURVEY.md §3.1) which fuse the
QKV projections into one matmul.  Here the same fusion holds (one
Dense(3·units) projection — one big MXU GEMM) and the O(L²) score
materialization is replaced by the flash kernel (O(L) memory,
SURVEY.md §5.7).
"""
from __future__ import annotations

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon.nn.basic_layers import Dense, Dropout, LayerNorm

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerDecoderCell"]


class MultiHeadAttention(HybridBlock):
    """Fused-QKV multi-head self-attention over (batch, seq, units).

    ``causal=True`` gives decoder (GPT) masking inside the flash kernel;
    an optional additive ``mask`` input (broadcastable to (B, 1, L, L),
    −inf at masked positions) carries encoder padding masks.
    """

    def __init__(self, units, num_heads, dropout=0.0, causal=False,
                 use_bias=True, dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by "
                             f"num_heads {num_heads}")
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self._attn_dropout = dropout
        with self.name_scope():
            self.qkv = Dense(3 * units, flatten=False, use_bias=use_bias,
                             in_units=units, dtype=dtype, prefix="qkv_")
            self.proj = Dense(units, flatten=False, use_bias=use_bias,
                              in_units=units, dtype=dtype, prefix="out_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None):
        B, L, U = x.shape
        H, D = self._heads, self._units // self._heads
        qkv = self.qkv(x)                                     # (B, L, 3U)
        qkv = F.reshape(qkv, shape=(B, L, 3, H, D))
        qkv = F.transpose(qkv, axes=(2, 0, 3, 1, 4))          # (3,B,H,L,D)
        q = F.reshape(F.slice_axis(qkv, axis=0, begin=0, end=1), shape=(B, H, L, D))
        k = F.reshape(F.slice_axis(qkv, axis=0, begin=1, end=2), shape=(B, H, L, D))
        v = F.reshape(F.slice_axis(qkv, axis=0, begin=2, end=3), shape=(B, H, L, D))
        out = F.flash_attention(q, k, v, mask, causal=self._causal,
                                dropout=self._attn_dropout)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)), shape=(B, L, U))
        out = self.proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out


class PositionwiseFFN(HybridBlock):
    """units → hidden (GELU) → units; both matmuls MXU-large."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.fc1 = Dense(hidden_size, flatten=False, in_units=units,
                             activation=activation, dtype=dtype,
                             prefix="fc1_")
            self.fc2 = Dense(units, flatten=False, in_units=hidden_size,
                             dtype=dtype, prefix="fc2_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.fc2(self.fc1(x))
        if self.drop is not None:
            out = self.drop(out)
        return out


class _TransformerCell(HybridBlock):
    """Pre-norm transformer layer: x + attn(ln(x)); x + ffn(ln(x))."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 causal=False, dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units, prefix="ln1_")
            self.attn = MultiHeadAttention(units, num_heads, dropout,
                                           causal=causal, dtype=dtype,
                                           prefix="attn_")
            self.ln2 = LayerNorm(in_channels=units, prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       dtype=dtype, prefix="ffn_")

    def hybrid_forward(self, F, x, mask=None):
        x = x + self.attn(self.ln1(x), mask) if mask is not None else \
            x + self.attn(self.ln1(x))
        return x + self.ffn(self.ln2(x))

    def decode_layer_arrays(self):
        """This layer's decode weights as a flat dict of device arrays —
        one slot per projection/bias/norm row, uniform across the GPT
        family so ``ops.decode_fused.stack_decode_weights`` can stack the
        whole block list into (NL, ...) arrays for the stacked-layer scan
        decode (``models.kv_generate``).  Missing biases are exported as
        zeros so every layer stacks to the same pytree."""
        import jax.numpy as jnp

        def wb(lyr, tag):
            w = lyr.weight.data()._data
            b = lyr.bias.data()._data if getattr(lyr, "bias", None) \
                is not None else jnp.zeros((w.shape[0],), w.dtype)
            return {f"{tag}_w": w, f"{tag}_b": b}

        out = {}
        out.update(wb(self.attn.qkv, "qkv"))
        out.update(wb(self.attn.proj, "proj"))
        out.update(wb(self.ffn.fc1, "fc1"))
        out.update(wb(self.ffn.fc2, "fc2"))
        out.update({
            "ln1_g": self.ln1.gamma.data()._data,
            "ln1_b": self.ln1.beta.data()._data,
            "ln2_g": self.ln2.gamma.data()._data,
            "ln2_b": self.ln2.beta.data()._data,
        })
        return out


class TransformerEncoderCell(_TransformerCell):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32", prefix=None, params=None):
        super().__init__(units, hidden_size, num_heads, dropout,
                         causal=False, dtype=dtype, prefix=prefix,
                         params=params)


class TransformerDecoderCell(_TransformerCell):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32", prefix=None, params=None):
        super().__init__(units, hidden_size, num_heads, dropout,
                         causal=True, dtype=dtype, prefix=prefix,
                         params=params)
