"""Llama-family decoder models (BASELINE config 5: "GPT-2 774M /
Llama-7B TP×DP"; SURVEY.md §7 Phase 4).

TPU-first architecture choices, matching the public Llama design:
pre-RMSNorm blocks, rotary position embeddings (no learned positional
table), grouped-query attention (kv_heads ≤ heads), SwiGLU FFN, untied
LM head — all over the same flash-attention + GSPMD machinery as GPT.
No reference analog (the reference's NLP stack is GluonNLP-era BERT);
this is capability the rebuild adds, like flash/ring attention.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..gluon.block import HybridBlock
from ..gluon.nn.basic_layers import Dense, Embedding, RMSNorm

__all__ = ["LlamaConfig", "Llama", "llama_tp_rules", "llama_tiny",
           "llama_7b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    max_length: int = 2048
    num_layers: int = 8
    units: int = 512
    num_heads: int = 8
    num_kv_heads: int = 8          # < num_heads => grouped-query attention
    hidden_size: int = 1376        # SwiGLU inner dim
    rope_base: float = 10000.0
    dtype: str = "float32"

    @property
    def num_params(self) -> int:
        u, h = self.units, self.hidden_size
        d = u // self.num_heads
        per_layer = (u * u + 2 * u * self.num_kv_heads * d + u * u  # qkvo
                     + 3 * u * h                                    # swiglu
                     + 2 * u)                                       # 2 rms
        return (self.vocab_size * u * 2    # embed + untied head
                + self.num_layers * per_layer + self.units)


class LlamaAttention(HybridBlock):
    """RoPE + grouped-query causal self-attention over (B, L, U)."""

    def __init__(self, units, num_heads, num_kv_heads, rope_base=10000.0,
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads or num_heads % num_kv_heads:
            raise ValueError(f"units {units} / heads {num_heads} / "
                             f"kv_heads {num_kv_heads} incompatible")
        self._units = units
        self._heads = num_heads
        self._kv_heads = num_kv_heads
        self._rope_base = float(rope_base)
        d = units // num_heads
        with self.name_scope():
            self.q_proj = Dense(units, flatten=False, use_bias=False,
                                in_units=units, dtype=dtype, prefix="q_")
            self.k_proj = Dense(num_kv_heads * d, flatten=False,
                                use_bias=False, in_units=units,
                                dtype=dtype, prefix="k_")
            self.v_proj = Dense(num_kv_heads * d, flatten=False,
                                use_bias=False, in_units=units,
                                dtype=dtype, prefix="v_")
            self.o_proj = Dense(units, flatten=False, use_bias=False,
                                in_units=units, dtype=dtype, prefix="o_")

    def hybrid_forward(self, F, x):
        B, L, U = x.shape
        H, KV = self._heads, self._kv_heads
        D = U // H
        q = F.transpose(F.reshape(self.q_proj(x), shape=(B, L, H, D)),
                        axes=(0, 2, 1, 3))
        k = F.transpose(F.reshape(self.k_proj(x), shape=(B, L, KV, D)),
                        axes=(0, 2, 1, 3))
        v = F.transpose(F.reshape(self.v_proj(x), shape=(B, L, KV, D)),
                        axes=(0, 2, 1, 3))
        q = F.rope(q, base=self._rope_base)
        k = F.rope(k, base=self._rope_base)
        if KV != H:  # grouped-query: repeat kv heads across query groups
            rep = H // KV
            k = F.repeat(k, repeats=rep, axis=1)
            v = F.repeat(v, repeats=rep, axis=1)
        out = F.flash_attention(q, k, v, causal=True)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        shape=(B, L, U))
        return self.o_proj(out)


class LlamaMLP(HybridBlock):
    """SwiGLU: down( silu(gate(x)) * up(x) )."""

    def __init__(self, units, hidden_size, dtype="float32", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.gate = Dense(hidden_size, flatten=False, use_bias=False,
                              in_units=units, dtype=dtype, prefix="gate_")
            self.up = Dense(hidden_size, flatten=False, use_bias=False,
                            in_units=units, dtype=dtype, prefix="up_")
            self.down = Dense(units, flatten=False, use_bias=False,
                              in_units=hidden_size, dtype=dtype,
                              prefix="down_")

    def hybrid_forward(self, F, x):
        g = self.gate(x)
        return self.down(g * F.sigmoid(g) * self.up(x))  # silu(gate)*up


class LlamaCell(HybridBlock):
    def __init__(self, cfg: LlamaConfig, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.rms1 = RMSNorm(in_channels=cfg.units, prefix="rms1_")
            self.attn = LlamaAttention(cfg.units, cfg.num_heads,
                                       cfg.num_kv_heads, cfg.rope_base,
                                       dtype=cfg.dtype, prefix="attn_")
            self.rms2 = RMSNorm(in_channels=cfg.units, prefix="rms2_")
            self.mlp = LlamaMLP(cfg.units, cfg.hidden_size,
                                dtype=cfg.dtype, prefix="mlp_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.rms1(x))
        return x + self.mlp(self.rms2(x))

    def decode_layer_arrays(self):
        """This layer's decode weights as a flat dict of device arrays
        (the Llama-family counterpart of
        ``_TransformerCell.decode_layer_arrays``): split q/k/v/o
        projections (GQA — k/v rows are KV·D wide), SwiGLU gate/up/down,
        and the two RMSNorm gammas.  The family contract is bias-free
        projections, so no bias slots are exported."""
        return {
            "q_w": self.attn.q_proj.weight.data()._data,
            "k_w": self.attn.k_proj.weight.data()._data,
            "v_w": self.attn.v_proj.weight.data()._data,
            "o_w": self.attn.o_proj.weight.data()._data,
            "gate_w": self.mlp.gate.weight.data()._data,
            "up_w": self.mlp.up.weight.data()._data,
            "down_w": self.mlp.down.weight.data()._data,
            "rms1_g": self.rms1.gamma.data()._data,
            "rms2_g": self.rms2.gamma.data()._data,
        }


class Llama(HybridBlock):
    """tokens (B, L) → logits (B, L, vocab)."""

    def __init__(self, config: LlamaConfig, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._cfg = config
        c = config
        with self.name_scope():
            self.wte = Embedding(c.vocab_size, c.units, dtype=c.dtype,
                                 prefix="wte_")
            self.blocks = []
            for i in range(c.num_layers):
                cell = LlamaCell(c, prefix=f"h{i}_")
                self.register_child(cell, f"h{i}")
                self.blocks.append(cell)
            self.ln_f = RMSNorm(in_channels=c.units, prefix="rmsf_")
            self.head = Dense(c.vocab_size, flatten=False, use_bias=False,
                              in_units=c.units, dtype=c.dtype,
                              prefix="head_")

    def forward(self, tokens, *args, **kwargs):
        x = self.wte(tokens)
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.ln_f(x))

    def stacked_decode_weights(self):
        """Every layer's decode weights stacked into (num_layers, ...)
        arrays — the Llama/GQA operand set of the stacked-layer
        ``lax.scan`` decode path (``models.kv_generate``).  See
        ``GPT.stacked_decode_weights`` and
        ``ops.decode_fused.stack_decode_weights``."""
        from ..ops.decode_fused import stack_decode_weights
        return stack_decode_weights(self.blocks)

    def generate(self, prompt_tokens, max_new_tokens=32, temperature=1.0,
                 top_k=0, seed=None):
        """Full-recompute autoregressive sampling (same loop as
        ``GPT.generate``).  For O(L)-per-token decode use
        ``models.kv_generate`` — it recognizes Llama blocks (RoPE via
        ``position_offset``, grouped-query KV cache)."""
        from .gpt import GPT
        return GPT.generate(self, prompt_tokens, max_new_tokens,
                            temperature, top_k, seed)


def llama_tp_rules(tp_axis: str = "tp"):
    """Megatron-style TP: q/k/v/gate/up split on the output dim,
    o/down on the input dim (one all-reduce per block pair via GSPMD);
    embedding + head sharded on vocab."""
    from ..parallel import P, ShardingRules
    return ShardingRules([
        (r".*(q|k|v|gate|up)_weight", P(tp_axis, None)),
        (r".*(o|down)_weight", P(None, tp_axis)),
        (r".*wte_weight", P(tp_axis, None)),
        (r".*head_weight", P(tp_axis, None)),
    ])


def _preset(**kw):
    def make(dtype="float32", **overrides):
        cfg = LlamaConfig(**{**kw, "dtype": dtype, **overrides})
        return Llama(cfg), cfg
    return make


llama_tiny = _preset(vocab_size=512, max_length=128, num_layers=2,
                     units=64, num_heads=4, num_kv_heads=2,
                     hidden_size=128)
llama_7b = _preset(vocab_size=32000, max_length=4096, num_layers=32,
                   units=4096, num_heads=32, num_kv_heads=32,
                   hidden_size=11008)
