"""mxnet_tpu.parallel — distribution over TPU meshes.

This package is the TPU-native answer to the reference's entire
communication stack (SURVEY.md §3.3, §5.8): the KVStore comm trees
(``src/kvstore/comm.h``), NCCL ring allreduce (``kvstore_nccl.h``), and the
ps-lite parameter server (``3rdparty/ps-lite``) all collapse into XLA
collectives over a ``jax.sharding.Mesh``:

- :mod:`mesh` — device-mesh construction (dp/tp/sp/pp axes, multi-host
  dcn×ici layouts) and the process-level bootstrap
  (``init_distributed`` = the reference's ``tools/launch.py`` env
  protocol, SURVEY.md §4.4).
- :mod:`collectives` — explicit NDArray-facing collectives
  (all_reduce/all_gather/reduce_scatter/ppermute) built on ``shard_map``;
  the reference's engine-scheduled comm ops become compiled XLA ops.
- :mod:`spmd` — ``ShardingRules`` (regex → PartitionSpec, the GSPMD
  analog of per-device replica lists) and ``SPMDTrainer``: ONE jitted
  train step (fwd+bwd+optimizer, donated buffers) over the mesh — the
  TPU-native form of the reference's record→backward→Trainer.step loop
  (SURVEY.md §4.2 "the whole step becomes one jit").
"""
from .mesh import (Mesh, P, make_mesh, current_mesh, default_mesh,
                   use_mesh, named_sharding, data_sharding,
                   replicated_sharding, init_distributed, local_mesh_axes,
                   barrier, global_put)
from .heartbeat import start_heartbeat, stop_heartbeat
from .collectives import (all_reduce, all_gather, reduce_scatter,
                          broadcast, ring_pass)
from .spmd import ShardingRules, shard_block, SPMDTrainer
from .pipeline import gpipe_apply, stack_stage_params

__all__ = [
    "Mesh", "P", "make_mesh", "current_mesh", "default_mesh", "use_mesh",
    "named_sharding", "data_sharding", "replicated_sharding",
    "init_distributed", "local_mesh_axes", "barrier", "global_put",
    "start_heartbeat", "stop_heartbeat",
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "ring_pass",
    "ShardingRules", "shard_block", "SPMDTrainer",
    "gpipe_apply", "stack_stage_params",
]
