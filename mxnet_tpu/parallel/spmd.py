"""GSPMD sharding rules and the fused SPMD train step.

Reference counterpart (SURVEY.md §4.2): the training step is
``record → forward → backward → Trainer.step`` with the KVStore doing the
cross-device reduction as separate engine ops.  TPU-native, that whole loop
is ONE jitted function over the mesh: forward+backward+optimizer with
donated buffers; GSPMD inserts the grad all-reduce (data axis) and the
tensor-parallel collectives (model axis) from sharding annotations — the
explicit KVStore machinery disappears into the compiler
(SURVEY.md §7 "KVStore").

``ShardingRules`` plays the role of the reference's per-device replica
lists / `group2ctx` placement (§3.3): a regex over parameter names maps
each param to a ``PartitionSpec`` on the mesh.
"""
from __future__ import annotations

import re
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .mesh import Mesh, P, default_mesh, global_put
from jax.sharding import NamedSharding

__all__ = ["ShardingRules", "shard_block", "SPMDTrainer"]


class ShardingRules:
    """Ordered (regex → PartitionSpec) rules for parameter sharding.

    Example (tensor parallel Dense layers on axis 'tp', everything else
    replicated)::

        rules = ShardingRules([
            (r".*dense\\d*\\.weight", P("tp", None)),
            (r".*\\.bias",            P("tp")),
        ])
        shard_block(net, mesh, rules)
    """

    def __init__(self, rules: Sequence, default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, name: str, shape=None, mesh: Optional[Mesh] = None):
        spec = self.default
        for pat, s in self.rules:
            if pat.match(name):
                spec = s
                break
        if shape is None or mesh is None:
            return spec
        return _fit_spec(spec, shape, mesh)


def _fit_spec(spec, shape, mesh: Mesh):
    """Drop spec axes that don't divide the corresponding dim, and truncate
    the spec to the array rank (so tiny test shapes and rank-mismatched
    rules still compile instead of erroring inside GSPMD)."""
    from .mesh import local_mesh_axes
    sizes = local_mesh_axes(mesh)
    out = []
    for i, s in enumerate(tuple(spec)[:len(shape)]):
        if s is None:
            out.append(None)
            continue
        ax_size = sizes.get(s) if isinstance(s, str) else None
        if isinstance(s, (tuple, list)):
            ax_size = 1
            for name in s:
                ax_size *= sizes[name]
        if ax_size is None or (shape[i] and shape[i] % ax_size == 0):
            out.append(s)
        else:
            out.append(None)
    return P(*out)


def shard_block(block, mesh: Optional[Mesh] = None,
                rules: Optional[ShardingRules] = None):
    """Annotate every initialized parameter of ``block`` with a
    ``NamedSharding`` from ``rules`` (device_put happens immediately;
    uninitialized params pick the sharding up at init)."""
    mesh = mesh or default_mesh()
    rules = rules or ShardingRules([])
    for name, p in block.collect_params().items():
        spec = rules.spec_for(name, p.shape if p.shape else None, mesh)
        p.set_sharding(NamedSharding(mesh, spec))
    return block


class SPMDTrainer:
    """One-jit training: ``step(data, label)`` runs forward, backward, and
    the optimizer update as a single compiled SPMD program over the mesh.

    - ``dp_axis`` shards the batch (data parallel); grads are reduced by
      GSPMD automatically because params are replicated (or sharded) over
      that axis.
    - param shardings come from ``rules`` (tensor/sequence parallel) or
      previously applied ``Parameter.set_sharding``.
    - param + optimizer-state buffers are donated: the update is in-place
      at the XLA level (the reference's ``static_alloc`` memory reuse).

    The imperative ``gluon.Trainer`` remains the API-parity path; this is
    the performance path (SURVEY.md §7 build plan, Phase 2).
    """

    def __init__(self, block, loss_fn: Callable, optimizer,
                 optimizer_params: Optional[dict] = None,
                 mesh: Optional[Mesh] = None,
                 rules: Optional[ShardingRules] = None,
                 dp_axis: str = "dp", donate: bool = True):
        from .. import optimizer as opt_mod

        self._block = block
        self._loss_fn = loss_fn
        self._mesh = mesh or default_mesh()
        self._rules = rules
        self._dp_axis = dp_axis
        self._donate = donate
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, opt_mod.Optimizer):
            self._opt = optimizer
            self._rescale = float(optimizer_params.pop(
                "rescale_grad", optimizer.rescale_grad))
        else:
            self._rescale = float(optimizer_params.pop("rescale_grad", 1.0))
            self._opt = opt_mod.create(optimizer, **optimizer_params)
        self._built = False
        self._step_fn = None
        self._multi_step_fn = None
        self._t = 0
        self._param_names: list = []
        self._train_params: list = []   # Parameter objs with grad_req != null
        self._frozen_params: list = []  # grad_req == null (e.g. running stats)
        self._train_vals: list = []
        self._frozen_vals: list = []
        self._opt_states: list = []

    # ------------------------------------------------------------------ #
    @property
    def optimizer(self):
        return self._opt

    @property
    def learning_rate(self):
        return self._opt.learning_rate

    def set_learning_rate(self, lr):
        self._opt.set_learning_rate(lr)

    # ------------------------------------------------------------------ #
    def _ensure_built(self, data, label):
        if self._built:
            return
        from ..ndarray.ndarray import NDArray
        from ..gluon.block import _no_hybrid
        from .. import autograd

        block = self._block
        params = block.collect_params()
        if any(p._data is None for p in params.values()):
            # materialize deferred shapes with one imperative forward
            with autograd.pause(train_mode=False), _no_hybrid():
                block(data if isinstance(data, NDArray) else
                      NDArray(jnp.asarray(data)))
            params = block.collect_params()
        if self._rules is not None:
            shard_block(block, self._mesh, self._rules)
        for name, p in params.items():
            if p._data is None:
                continue
            self._param_names.append(name)
            if p.grad_req != "null":
                self._train_params.append(p)
            else:
                self._frozen_params.append(p)
        self._train_vals = [p._data._data for p in self._train_params]
        self._frozen_vals = [p._data._data for p in self._frozen_params]
        self._opt_states = [
            self._opt.create_state_multi_precision(i, p.data())
            for i, p in enumerate(self._train_params)]
        if jax.process_count() > 1:
            # on a pod the jitted step's in_shardings span processes:
            # host/local-committed values cannot be auto-placed by jit,
            # so assemble the global params/states up front
            repl, shard_of, state_shardings = self._shardings()
            self._train_vals = [global_put(v, shard_of(p)) for v, p in
                                zip(self._train_vals,
                                    self._train_params)]
            self._frozen_vals = [global_put(v, shard_of(p)) for v, p in
                                 zip(self._frozen_vals,
                                     self._frozen_params)]
            self._opt_states = [
                jax.tree.map(lambda a, sh: global_put(a, sh)
                             if hasattr(a, "shape") else a, s,
                             state_shardings(s, p))
                for s, p in zip(self._opt_states, self._train_params)]
        self._step_fn = self._compile()
        self._built = True

    # ------------------------------------------------------------------ #
    def _forward_loss(self, key, train_vals, frozen_vals, data, label,
                      aux_out):
        """Pure loss: swap param values into the block, run block + loss
        imperatively (ops dispatch straight to jnp on tracers), collect aux
        (running-stat) updates."""
        from ..ndarray.ndarray import NDArray
        from ..gluon.block import trace_scope
        from ..gluon.parameter import params_swapped

        all_params = self._train_params + self._frozen_params
        all_vals = list(train_vals) + list(frozen_vals)
        with trace_scope(key, training=True) as aux:
            with params_swapped(all_params, all_vals):
                out = self._block(NDArray(data))
                out0 = out[0] if isinstance(out, (list, tuple)) else out
                loss = self._loss_fn(out0, NDArray(label))
                loss_val = jnp.mean(loss._data if isinstance(loss, NDArray)
                                    else loss)
        aux_out.append([(p, jax.lax.stop_gradient(v))
                        for (p, v) in aux.values()])
        return loss_val

    def _make_step_fn(self):
        """The pure one-step body shared by the single-step jit and the
        multi-step scan."""
        opt = self._opt
        mp_flags = []
        for s, p in zip(self._opt_states, self._train_params):
            w = p._data._data
            mp_flags.append(
                opt.multi_precision and w.dtype in (jnp.float16, jnp.bfloat16)
                and isinstance(s, tuple) and len(s) == 2
                and getattr(s[0], "dtype", None) == jnp.float32)
        lr_mults = [float(p.lr_mult) for p in self._train_params]
        wd_mults = [float(p.wd_mult) for p in self._train_params]

        def step_fn(train_vals, opt_states, frozen_vals, key, lr, rescale,
                    t, data, label):
            aux_box: list = []

            def loss_of(tv):
                return self._forward_loss(key, tv, frozen_vals, data,
                                          label, aux_box)

            loss, grads = jax.value_and_grad(loss_of)(tuple(train_vals))
            aux_pairs = aux_box[-1] if aux_box else []

            new_vals, new_states = [], []
            for i, (w, g, s, mp) in enumerate(
                    zip(train_vals, grads, opt_states, mp_flags)):
                lr_i = lr * lr_mults[i]
                wd_i = opt.wd * wd_mults[i]
                if mp:
                    master, inner = s
                    g32 = g.astype(jnp.float32) * rescale
                    if opt.clip_gradient is not None:
                        g32 = jnp.clip(g32, -opt.clip_gradient,
                                       opt.clip_gradient)
                    nm, ni = opt._update_rule(master, g32, inner, lr_i,
                                              wd_i, t)
                    new_vals.append(nm.astype(w.dtype))
                    new_states.append((nm, jax.tree.map(
                        lambda a, b: b.astype(a.dtype) if hasattr(
                            a, "dtype") else b, inner, ni)))
                else:
                    # CRITICAL dtype discipline: the traced f32 scalars
                    # (rescale/lr) promote bf16 math to f32; without the
                    # casts below one step() silently turns the whole
                    # model f32 and the MXU runs at 1/2-1/4 rate
                    g = (g * rescale).astype(w.dtype)
                    if opt.clip_gradient is not None:
                        g = jnp.clip(g, -opt.clip_gradient,
                                     opt.clip_gradient)
                    nw, ns = opt._update_rule(w, g, s, lr_i, wd_i, t)
                    new_vals.append(nw.astype(w.dtype))
                    new_states.append(jax.tree.map(
                        lambda a, b: b.astype(a.dtype) if hasattr(
                            a, "dtype") else b, s, ns))

            # map aux updates back to frozen-param slots
            aux_by_id = {id(p): v for p, v in aux_pairs}
            new_frozen = [aux_by_id.get(id(p), v)
                          for p, v in zip(self._frozen_params, frozen_vals)]
            return loss, list(new_vals), new_states, new_frozen

        return step_fn

    def _shardings(self):
        mesh = self._mesh
        repl = NamedSharding(mesh, P())

        def shard_of(p):
            return p._sharding if p._sharding is not None else repl

        def state_shardings(s, p):
            psh = shard_of(p)
            return jax.tree.map(
                lambda leaf: psh if getattr(leaf, "shape", None)
                == p._data._data.shape else repl, s)

        return repl, shard_of, state_shardings

    def _compile(self):
        step_fn = self._make_step_fn()
        mesh = self._mesh
        repl, shard_of, state_shardings = self._shardings()

        in_shardings = (
            [shard_of(p) for p in self._train_params],
            [state_shardings(s, p)
             for s, p in zip(self._opt_states, self._train_params)],
            [shard_of(p) for p in self._frozen_params],
            repl, repl, repl, repl,
            NamedSharding(mesh, P(self._dp_axis)),
            NamedSharding(mesh, P(self._dp_axis)),
        )
        out_shardings = (
            repl,               # loss
            in_shardings[0],    # new param values keep their layout
            in_shardings[1],    # optimizer states likewise
            in_shardings[2],    # frozen/aux values likewise
        )
        donate = (0, 1) if self._donate else ()
        return jax.jit(step_fn, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    def _compile_multi(self):
        """N steps inside one compiled program via ``lax.scan`` —
        amortizes host dispatch (and tunnel round-trips) over N steps; the
        latency-hiding answer to the reference's engine pipelining."""
        step_fn = self._make_step_fn()
        mesh = self._mesh
        repl, shard_of, state_shardings = self._shardings()

        def multi_fn(train_vals, opt_states, frozen_vals, keys, lr, rescale,
                     t0, datas, labels):
            def body(carry, xs):
                tv, os_, fv, t = carry
                key, d, l = xs
                loss, ntv, nos, nfv = step_fn(tv, os_, fv, key, lr,
                                              rescale, t, d, l)
                return (tuple(ntv), nos, nfv, t + 1), loss

            (tv, os_, fv, _), losses = jax.lax.scan(
                body, (tuple(train_vals), opt_states, frozen_vals, t0),
                (keys, datas, labels))
            return losses, list(tv), os_, fv

        data_sh = NamedSharding(mesh, P(None, self._dp_axis))
        in_shardings = (
            [shard_of(p) for p in self._train_params],
            [state_shardings(s, p)
             for s, p in zip(self._opt_states, self._train_params)],
            [shard_of(p) for p in self._frozen_params],
            repl, repl, repl, repl,
            data_sh, data_sh,
        )
        out_shardings = (repl, in_shardings[0], in_shardings[1],
                         in_shardings[2])
        donate = (0, 1) if self._donate else ()
        return jax.jit(multi_fn, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    # ------------------------------------------------------------------ #
    def run_steps(self, data, label, batch_size: Optional[int] = None):
        """Run N fused steps in ONE dispatch.  ``data``/``label`` carry a
        leading steps axis: (N, batch, ...).  Returns the (N,) loss
        array as an NDArray."""
        from ..ndarray.ndarray import NDArray
        from .. import random as mxrandom

        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        l = label._data if isinstance(label, NDArray) else jnp.asarray(label)
        n = d.shape[0]
        self._ensure_built(NDArray(d[0]), NDArray(l[0]))
        if self._multi_step_fn is None:
            self._multi_step_fn = self._compile_multi()
        keys = jax.random.split(mxrandom.next_key(), n)
        lr = jnp.asarray(self._opt.learning_rate, jnp.float32)
        rescale = jnp.asarray(
            self._rescale / (batch_size if batch_size else 1.0), jnp.float32)
        t0 = jnp.asarray(self._t + 1, jnp.int32)
        sh = NamedSharding(self._mesh, P(None, self._dp_axis))
        if jax.process_count() > 1:
            repl = NamedSharding(self._mesh, P())
            keys, lr, rescale, t0 = (global_put(a, repl) for a in
                                     (keys, lr, rescale, t0))
        d = global_put(d, sh)
        l = global_put(l, sh)
        losses, self._train_vals, self._opt_states, self._frozen_vals = \
            self._multi_step_fn(self._train_vals, self._opt_states,
                                self._frozen_vals, keys, lr, rescale, t0,
                                d, l)
        self._t += n
        self._opt.num_update = self._t
        for p, v in zip(self._train_params, self._train_vals):
            p._data._data = v
        for p, v in zip(self._frozen_params, self._frozen_vals):
            p._data._data = v
        return NDArray(losses)

    def step_hlo_op_count(self, data, label):
        """Optimized-HLO instruction count of the compiled one-step
        program (``profiler_xla.hlo_op_count`` convention: fusion bodies
        collapse to one op, while bodies count once) — the static
        sequencer-overhead metric behind BASELINE.md's round-3 anatomy
        (the BERT step's wall-vs-device MFU gap is ~5,300 ops x ~1 us of
        fixed per-op cost).  Compiles but does not execute; donation is
        irrelevant at lowering time."""
        from ..ndarray.ndarray import NDArray
        from .. import profiler_xla

        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        l = label._data if isinstance(label, NDArray) \
            else jnp.asarray(label)
        self._ensure_built(NDArray(d), NDArray(l))
        lr = jnp.asarray(self._opt.learning_rate, jnp.float32)
        rescale = jnp.asarray(self._rescale, jnp.float32)
        t = jnp.asarray(max(self._t, 1), jnp.int32)
        # a CONSTANT key, not random.next_key(): only shapes matter for
        # lowering, and a diagnostic must not advance the global PRNG
        # stream (it would silently change dropout/sampling streams of
        # the surrounding training run)
        key = jax.random.PRNGKey(0)
        return profiler_xla.hlo_op_count(
            self._step_fn, self._train_vals, self._opt_states,
            self._frozen_vals, key, lr, rescale, t, d, l)

    def step(self, data, label, batch_size: Optional[int] = None):
        """Run one fused train step; returns the (device-async) loss as an
        NDArray.  ``batch_size`` defaults to the global batch dim (grad is
        the mean loss's grad, so rescale defaults to 1)."""
        from ..ndarray.ndarray import NDArray
        from .. import random as mxrandom

        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        l = label._data if isinstance(label, NDArray) else jnp.asarray(label)
        self._ensure_built(NDArray(d), NDArray(l))
        self._t += 1
        self._opt.num_update = self._t
        lr = jnp.asarray(self._opt.learning_rate, jnp.float32)
        rescale = jnp.asarray(
            self._rescale / (batch_size if batch_size else 1.0), jnp.float32)
        t = jnp.asarray(self._t, jnp.int32)
        key = mxrandom.next_key()
        if jax.process_count() > 1:
            repl = NamedSharding(self._mesh, P())
            key, lr, rescale, t = (global_put(a, repl) for a in
                                   (key, lr, rescale, t))
        d = global_put(d, NamedSharding(self._mesh, P(self._dp_axis)))
        l = global_put(l, NamedSharding(self._mesh, P(self._dp_axis)))
        loss, self._train_vals, self._opt_states, self._frozen_vals = \
            self._step_fn(self._train_vals, self._opt_states,
                          self._frozen_vals, key, lr,
                          rescale, t, d, l)
        # sync new values back into the block's Parameters (rebind is
        # async — no host transfer)
        for p, v in zip(self._train_params, self._train_vals):
            p._data._data = v
        for p, v in zip(self._frozen_params, self._frozen_vals):
            p._data._data = v
        return NDArray(loss)
