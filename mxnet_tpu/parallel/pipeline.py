"""Pipeline parallelism (GPipe schedule over a mesh axis).

SURVEY.md §3.3 marks PP "optional later phase" for the reference (which has
none — only manual ``group2ctx`` placement).  TPU-native implementation:
stages live on a ``pp`` mesh axis, activations flow stage-to-stage with
``ppermute`` (ICI-neighbor traffic), and microbatches fill the pipeline on
a GPipe schedule — M microbatches over S stages cost M+S-1 ticks, all
inside ONE jitted ``shard_map`` (XLA overlaps the permute with compute).

The schedule is differentiable end-to-end: ``jax.grad`` through
``gpipe_apply`` backpropagates the reverse schedule automatically, so a
pipelined train step is just ``jax.value_and_grad(loss ∘ gpipe_apply)``.

Constraint (by design): activations circulate a ring, so the stage input
and output shapes must match — run embeddings/heads outside the pipelined
trunk (the standard GPipe decomposition).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from .._jax_compat import NO_CHECK, shard_map

from .mesh import Mesh, P, default_mesh, local_mesh_axes

__all__ = ["gpipe_apply", "stack_stage_params"]


def stack_stage_params(stage_params_list):
    """Stack per-stage parameter pytrees on a new leading axis (the ``pp``
    sharding axis): [tree_0, ..., tree_{S-1}] → tree of (S, ...) arrays."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *stage_params_list)


def gpipe_apply(stage_fn: Callable, stage_params, x, mesh: Mesh = None,
                axis: str = "pp", microbatches: int = None,
                param_specs=None, batch_axis: str = None):
    """Run ``x`` through S pipeline stages with a GPipe schedule.

    - ``stage_fn(params_i, h) -> h`` — one stage (same structure every
      stage, per-stage weights; h-shape invariant).
    - ``stage_params`` — pytree with leading axis S (see
      :func:`stack_stage_params`), sharded over ``axis``.
    - ``x`` — (batch, ...) input, split into ``microbatches`` chunks along
      axis 0 (default S, the minimum that fills the pipeline).
    - ``param_specs`` — optional pytree of ``PartitionSpec`` for the
      stacked stage params (leading axis must be ``axis``), enabling
      pp×tp composition: shard stage weights over a tensor axis too and
      do the tp collectives (``lax.psum``/``lax.all_gather``) inside
      ``stage_fn`` itself.  With ``param_specs`` the stage must also
      preserve the activation DTYPE (not just shape): in-shard
      collectives cannot be eval_shape'd up front, so a dtype-changing
      stage surfaces as a scan-carry mismatch instead of the pure-pp
      path's ring-invariance error.
    - ``batch_axis`` — optional mesh axis to shard the microbatch dim
      over (dp×pp composition); the output stays sharded over it.

    Returns the final stage's (batch, ...) output, replicated over
    ``axis`` (and sharded over ``batch_axis`` if given).
    """
    from ..ndarray.ndarray import NDArray

    mesh = mesh or default_mesh()
    S = local_mesh_axes(mesh)[axis]
    M = microbatches or S
    xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    params = jax.tree.map(
        lambda a: a._data if isinstance(a, NDArray) else jnp.asarray(a),
        stage_params)
    B = xv.shape[0]
    if B % M:
        raise ValueError(f"batch {B} must divide into {M} microbatches")
    mb = B // M
    xs = xv.reshape((M, mb) + xv.shape[1:])

    out_dtype = xv.dtype
    if param_specs is None:
        # pure-pp path: stage_fn sees global microbatch shapes, so the
        # ring-invariance precondition is checkable up front.  (With
        # param_specs the stage may use in-shard collectives, which
        # cannot be eval_shape'd outside shard_map.)
        p0 = jax.tree.map(lambda a: a[0], params)
        out_aval = jax.eval_shape(stage_fn, p0, jax.ShapeDtypeStruct(
            (mb,) + xv.shape[1:], xv.dtype))
        if tuple(out_aval.shape) != (mb,) + tuple(xv.shape[1:]):
            raise ValueError(
                "gpipe_apply requires ring-invariant activations: stage "
                f"output {tuple(out_aval.shape)} != input "
                f"{(mb,) + tuple(xv.shape[1:])}; keep embeddings/heads "
                "outside the pipelined trunk")
        out_dtype = out_aval.dtype

    def shard_fn(local_params, xs_local):
        my = lax.axis_index(axis)
        lp = jax.tree.map(lambda a: a[0], local_params)  # drop local S=1
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(state, t):
            prev = lax.ppermute(state, axis, fwd)
            x_t = xs_local[jnp.minimum(t, M - 1)].astype(state.dtype)
            inp = jnp.where(my == 0, x_t, prev)
            out = stage_fn(lp, inp)
            return out, out

        state0 = jnp.zeros(xs_local.shape[1:], out_dtype)
        # the carry varies per pp shard; mark the init accordingly
        # (jax 0.4.x predates pcast/pvary — there the rep checker is
        # simply disabled below and no mark is needed)
        if hasattr(lax, "pcast"):
            state0 = lax.pcast(state0, (axis,), to="varying")
        elif hasattr(lax, "pvary"):
            state0 = lax.pvary(state0, (axis,))
        _, hist = lax.scan(tick, state0, jnp.arange(M + S - 1))
        # the final stage emits microbatch m at tick m + S - 1
        outs = lax.dynamic_slice_in_dim(hist, S - 1, M, axis=0)
        mine = jnp.where(my == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(mine, axis)  # replicate the true outputs

    pspec = (param_specs if param_specs is not None
             else jax.tree.map(lambda a: P(axis), params))
    params = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), pspec))
    x_spec = P(None, batch_axis) if batch_axis else P()
    kwargs = {}
    if "check_rep" in NO_CHECK:
        # jax 0.4.x: the old replication checker has no pvary marks to
        # see through the ppermute ring — disable it outright
        kwargs.update(NO_CHECK)
    elif param_specs is not None or batch_axis:
        # in-stage collectives (tp) defeat the static replication checker
        kwargs.update(NO_CHECK)
    fn = shard_map(shard_fn, mesh=mesh, in_specs=(pspec, x_spec),
                   out_specs=x_spec, **kwargs)
    out = fn(params, xs)
    result = out.reshape((B,) + out.shape[2:])
    return NDArray(result) if isinstance(x, NDArray) else result
