"""Pipeline parallelism (GPipe schedule over a mesh axis).

SURVEY.md §3.3 marks PP "optional later phase" for the reference (which has
none — only manual ``group2ctx`` placement).  TPU-native implementation:
stages live on a ``pp`` mesh axis, activations flow stage-to-stage with
``ppermute`` (ICI-neighbor traffic), and microbatches fill the pipeline on
a GPipe schedule — M microbatches over S stages cost M+S-1 ticks, all
inside ONE jitted ``shard_map`` (XLA overlaps the permute with compute).

The schedule is differentiable end-to-end: ``jax.grad`` through
``gpipe_apply`` backpropagates the reverse schedule automatically, so a
pipelined train step is just ``jax.value_and_grad(loss ∘ gpipe_apply)``.

Constraint (by design): activations circulate a ring, so the stage input
and output shapes must match — run embeddings/heads outside the pipelined
trunk (the standard GPipe decomposition).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import NamedSharding

from .mesh import Mesh, P, default_mesh, local_mesh_axes

__all__ = ["gpipe_apply", "stack_stage_params"]


def stack_stage_params(stage_params_list):
    """Stack per-stage parameter pytrees on a new leading axis (the ``pp``
    sharding axis): [tree_0, ..., tree_{S-1}] → tree of (S, ...) arrays."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *stage_params_list)


def gpipe_apply(stage_fn: Callable, stage_params, x, mesh: Mesh = None,
                axis: str = "pp", microbatches: int = None):
    """Run ``x`` through S pipeline stages with a GPipe schedule.

    - ``stage_fn(params_i, h) -> h`` — one stage (same structure every
      stage, per-stage weights; h-shape invariant).
    - ``stage_params`` — pytree with leading axis S (see
      :func:`stack_stage_params`), sharded over ``axis``.
    - ``x`` — (batch, ...) input, split into ``microbatches`` chunks along
      axis 0 (default S, the minimum that fills the pipeline).

    Returns the final stage's (batch, ...) output, replicated.
    """
    from ..ndarray.ndarray import NDArray

    mesh = mesh or default_mesh()
    S = local_mesh_axes(mesh)[axis]
    M = microbatches or S
    xv = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    params = jax.tree.map(
        lambda a: a._data if isinstance(a, NDArray) else jnp.asarray(a),
        stage_params)
    B = xv.shape[0]
    if B % M:
        raise ValueError(f"batch {B} must divide into {M} microbatches")
    mb = B // M
    xs = xv.reshape((M, mb) + xv.shape[1:])

    p0 = jax.tree.map(lambda a: a[0], params)
    out_aval = jax.eval_shape(stage_fn, p0, jax.ShapeDtypeStruct(
        (mb,) + xv.shape[1:], xv.dtype))
    if tuple(out_aval.shape) != (mb,) + tuple(xv.shape[1:]):
        raise ValueError(
            "gpipe_apply requires ring-invariant activations: stage output "
            f"{tuple(out_aval.shape)} != input {(mb,) + tuple(xv.shape[1:])};"
            " keep embeddings/heads outside the pipelined trunk")

    def shard_fn(local_params, xs_full):
        my = lax.axis_index(axis)
        lp = jax.tree.map(lambda a: a[0], local_params)  # drop local S=1
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(state, t):
            prev = lax.ppermute(state, axis, fwd)
            x_t = xs_full[jnp.minimum(t, M - 1)].astype(out_aval.dtype)
            inp = jnp.where(my == 0, x_t, prev)
            out = stage_fn(lp, inp)
            return out, out

        state0 = jnp.zeros(out_aval.shape, out_aval.dtype)
        # the carry varies per pp shard; mark the init accordingly
        state0 = lax.pcast(state0, (axis,), to="varying") \
            if hasattr(lax, "pcast") else lax.pvary(state0, (axis,))
        _, hist = lax.scan(tick, state0, jnp.arange(M + S - 1))
        # the final stage emits microbatch m at tick m + S - 1
        outs = lax.dynamic_slice_in_dim(hist, S - 1, M, axis=0)
        mine = jnp.where(my == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(mine, axis)  # replicate the true outputs

    pspec = jax.tree.map(lambda a: P(axis), params)
    params = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), pspec))
    fn = shard_map(shard_fn, mesh=mesh, in_specs=(pspec, P()),
                   out_specs=P())
    out = fn(params, xs)
    result = out.reshape((B,) + out.shape[2:])
    return NDArray(result) if isinstance(x, NDArray) else result
