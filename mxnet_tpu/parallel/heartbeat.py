"""Per-rank heartbeat writer — the rank half of the supervised launch.

``tools/launch.py`` (the supervisor) gives every rank a private
``MXNET_HEARTBEAT_FILE`` and watches its mtime: a rank whose file goes
silent past ``--heartbeat-timeout`` is declared wedged and the whole
job is torn down with a diagnostic instead of hanging in a collective
forever (the reference tracker's dead-worker detection,
ROADMAP "fault-tolerant rendezvous").

This module is the writer: :func:`start_heartbeat` runs a daemon
thread touching the file every ``MXNET_HEARTBEAT_INTERVAL`` seconds
(file content = ``pid beat_count`` for post-mortems; the supervisor
only reads mtime).  ``parallel.init_distributed`` calls it before the
coordinator rendezvous — a rank stuck in ``jax.distributed`` init
still beats, so the supervisor distinguishes "slow rendezvous" from
"dead rank" — and ``kvstore_server``'s parked server role beats too.

The beat loop is a ``launch.heartbeat`` fault-injection site
(``MXNET_FAULT_INJECT=launch.heartbeat:kill:2`` is how the chaos tests
kill one rank of a launched job mid-run).
"""
from __future__ import annotations

import os
import threading

from ..telemetry.faults import fault_point

__all__ = ["start_heartbeat", "stop_heartbeat", "heartbeat_path",
           "heartbeat_interval"]

_lock = threading.Lock()
_state = {"thread": None, "stop": None, "path": None}


def heartbeat_path():
    """The supervisor-assigned beat file (None = unsupervised run)."""
    return os.environ.get("MXNET_HEARTBEAT_FILE") or None


def heartbeat_interval():
    from ..base import parse_seconds

    val = parse_seconds("MXNET_HEARTBEAT_INTERVAL",
                        os.environ.get("MXNET_HEARTBEAT_INTERVAL",
                                       "1.0"))
    return max(val, 0.01)


def _beat_once(path, count):
    fault_point("launch.heartbeat", path=path, beat=count)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(f"{os.getpid()} {count}\n")


def start_heartbeat(path=None, interval=None):
    """Start (idempotently) the daemon beat thread; returns it, or
    ``None`` when no heartbeat file is configured.  The first beat is
    written synchronously on the caller's thread, so the supervisor
    sees a live rank the moment this returns — before any slow
    import/rendezvous work begins."""
    path = path or heartbeat_path()
    if path is None:
        return None
    interval = interval if interval is not None else heartbeat_interval()
    with _lock:
        th = _state["thread"]
        if th is not None and th.is_alive():
            if _state["path"] == path:
                return th
            # re-pointed at a new file: stop the old beater first — a
            # leaked thread would keep the OLD file fresh forever, so a
            # supervisor watching it could never see this rank as dead
            _state["stop"].set()
            th.join(timeout=2.0)
        _beat_once(path, 0)
        stop = threading.Event()

        def _loop():
            count = 1
            while not stop.wait(interval):
                try:
                    _beat_once(path, count)
                except OSError:
                    return   # beat dir torn down: the job is ending
                count += 1

        th = threading.Thread(target=_loop, name="mxnet-heartbeat",
                              daemon=True)
        _state["thread"] = th
        _state["stop"] = stop
        _state["path"] = path
        th.start()
    return th


def stop_heartbeat():
    """Stop the beat thread (tests / clean shutdown)."""
    with _lock:
        th, stop = _state["thread"], _state["stop"]
        _state["thread"] = None
        _state["stop"] = None
        _state["path"] = None
    if stop is not None:
        stop.set()
    if th is not None and th.is_alive():
        th.join(timeout=2.0)
