"""Explicit collectives over the mesh.

Reference counterpart (SURVEY.md §5.8): ``CommDevice``/``CommDeviceTree``
P2P reduction trees, ``KVStoreNCCL`` ring allreduce, ps-lite cross-node
push/pull.  TPU-native: every collective is a ``shard_map``-wrapped XLA
collective (psum / all_gather / psum_scatter / ppermute) compiled onto
ICI/DCN; there is no engine scheduling — overlap comes from XLA's
latency-hiding scheduler.

These helpers take and return ``NDArray``/jax arrays whose leading axis is
sharded over ``axis`` (or replicated inputs for broadcast).  They are the
building blocks of ``KVStore('tpu')`` and of the multi-host `dist_sync`
path; inside a jitted SPMD step you normally never call them — GSPMD
inserts the equivalent ops from sharding annotations.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from .._jax_compat import NO_CHECK as _NO_CHECK, shard_map
from .mesh import Mesh, P, default_mesh, local_mesh_axes

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "ring_pass", "dp_sharding"]


def dp_sharding(mesh: Optional[Mesh] = None, axis: str = "dp"):
    """``NamedSharding`` laying a batch out over the data-parallel axis
    (delegates to :func:`..mesh.data_sharding` — one definition).

    The fused train step (``Trainer.fused_step(...,
    data_sharding=dp_sharding(mesh))``) places its batch operands with
    this sharding; with the parameters replicated (or GSPMD-sharded) over
    the same mesh, the compiled step then CONTAINS the cross-replica
    gradient all-reduce — the reference's per-step KVStore pushpull phase
    folded into the one traced executable, inserted by GSPMD instead of
    engine-scheduled ops (SURVEY.md §7 "KVStore")."""
    from .mesh import data_sharding
    return data_sharding(mesh, axis)


def _unwrap(x):
    from ..ndarray.ndarray import NDArray
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _wrap_like(val, ref):
    from ..ndarray.ndarray import NDArray
    if isinstance(ref, NDArray):
        return NDArray(val)
    return val


_OPS = {
    "sum": jax.lax.psum,
    "mean": jax.lax.pmean,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def all_reduce(x, mesh: Optional[Mesh] = None, axis: str = "dp",
               op: str = "sum"):
    """All-reduce ``x`` (sharded on its leading dim over ``axis``) — the
    result is the reduced value, replicated over ``axis``, with the same
    per-shard shape.  Equivalent of one NCCL ring all-reduce
    (``KVStoreNCCL``)."""
    if op not in _OPS:
        raise ValueError(f"unknown reduce op {op}")
    mesh = mesh or default_mesh()
    red = _OPS[op]
    data = _unwrap(x)

    fn = shard_map(lambda v: red(v, axis), mesh=mesh,
                   in_specs=P(axis), out_specs=P())
    # input must be laid out sharded over axis; put it there if it isn't
    data = jax.device_put(data, NamedSharding(mesh, P(axis)))
    return _wrap_like(fn(data), x)


def all_gather(x, mesh: Optional[Mesh] = None, axis: str = "dp",
               tiled: bool = True):
    """Gather shards along the leading dim: per-shard (s, ...) → full
    (s*n, ...) on every device."""
    mesh = mesh or default_mesh()
    data = jax.device_put(_unwrap(x), NamedSharding(mesh, P(axis)))
    fn = shard_map(
        lambda v: jax.lax.all_gather(v, axis, tiled=tiled),
        mesh=mesh, in_specs=P(axis), out_specs=P(), **_NO_CHECK)
    return _wrap_like(fn(data), x)


def reduce_scatter(x, mesh: Optional[Mesh] = None, axis: str = "dp",
                   op: str = "sum"):
    """Reduce-scatter: every shard holds the (full-size) addend; the result
    is the reduced value scattered over ``axis`` along the leading dim.
    Equivalent of the reference's tree reduce-scatter phase
    (``comm_tree.h``)."""
    mesh = mesh or default_mesh()
    n = local_mesh_axes(mesh)[axis]
    data = _unwrap(x)
    if data.shape[0] % n:
        raise ValueError(
            f"leading dim {data.shape[0]} not divisible by axis size {n}")
    # replicate input, psum_scatter inside shard_map
    data = jax.device_put(data, NamedSharding(mesh, P()))
    fn = shard_map(
        lambda v: jax.lax.psum_scatter(v, axis, scatter_dimension=0,
                                       tiled=True),
        mesh=mesh, in_specs=P(), out_specs=P(axis))
    return _wrap_like(fn(data), x)


def broadcast(x, mesh: Optional[Mesh] = None, axis: str = "dp",
              root: int = 0):
    """Broadcast shard ``root``'s value to all devices on ``axis`` (the
    reference's CommDevice broadcast phase)."""
    mesh = mesh or default_mesh()
    n = local_mesh_axes(mesh)[axis]
    if not 0 <= root < n:
        raise ValueError(f"broadcast root {root} out of range for axis "
                         f"{axis!r} of size {n}")
    data = jax.device_put(_unwrap(x), NamedSharding(mesh, P(axis)))

    def _bcast(v):
        idx = jax.lax.axis_index(axis)
        # where (not multiply): inf/NaN on non-root shards must not leak
        # through the psum
        contrib = jnp.where(idx == root, v, jnp.zeros_like(v))
        return jax.lax.psum(contrib, axis)

    fn = shard_map(_bcast, mesh=mesh, in_specs=P(axis), out_specs=P())
    return _wrap_like(fn(data), x)


def ring_pass(x, mesh: Optional[Mesh] = None, axis: str = "sp",
              shift: int = 1):
    """Rotate shards around the ``axis`` ring by ``shift`` steps
    (collective-permute over ICI) — the primitive under ring attention
    (SURVEY.md §5.7, new capability vs the reference)."""
    mesh = mesh or default_mesh()
    n = local_mesh_axes(mesh)[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]
    data = jax.device_put(_unwrap(x), NamedSharding(mesh, P(axis)))
    fn = shard_map(
        partial(jax.lax.ppermute, axis_name=axis, perm=perm),
        mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    return _wrap_like(fn(data), x)
