"""Device meshes and the multi-host bootstrap.

Reference counterpart: context groups + kvstore device lists
(``mx.gpu(i)`` lists sliced by ``DataParallelExecutorGroup``) and the
ps-lite/ZMQ node bootstrap driven by ``tools/launch.py`` env vars
(``DMLC_PS_ROOT_URI``/``DMLC_ROLE``/..., SURVEY.md §4.4).  TPU-native:
one ``jax.sharding.Mesh`` names the axes (``dp``/``tp``/``sp``/``pp``)
and XLA emits the collectives; multi-host membership comes from
``jax.distributed.initialize`` instead of a ZMQ Van.
"""
from __future__ import annotations

import inspect
import math
import os
import threading
import time
from typing import Optional, Sequence

import jax
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["Mesh", "P", "make_mesh", "current_mesh", "default_mesh",
           "use_mesh", "named_sharding", "data_sharding",
           "replicated_sharding", "init_distributed", "local_mesh_axes",
           "barrier", "global_put"]

_state = threading.local()


def make_mesh(axes=None, devices: Optional[Sequence] = None) -> Mesh:
    """Build a named device mesh.

    ``axes``: dict ``{name: size}`` in major→minor order; at most one size
    may be ``-1`` ("fill with the remaining devices").  Defaults to a pure
    data-parallel mesh ``{'dp': n_devices}``.  For multi-host topologies put
    the cross-host axis first (major) so its collectives ride DCN while the
    minor axes stay on ICI (SURVEY.md §5.8).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    if isinstance(axes, (list, tuple)):
        axes = dict(axes)
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise MXNetError("at most one mesh axis may be -1")
    fixed = 1
    for s in sizes:
        if s != -1:
            fixed *= s
    if n % fixed:
        raise MXNetError(
            f"mesh axes {axes} do not divide {n} devices")
    if -1 in sizes:
        sizes[sizes.index(-1)] = n // fixed
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(
            f"mesh axes {dict(zip(names, sizes))} use {total} devices, "
            f"have {n}")
    arr = onp.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh() -> Mesh:
    """The ambient mesh: the active ``use_mesh`` if any, else a cached pure-DP
    mesh over all devices."""
    cur = current_mesh()
    if cur is not None:
        return cur
    if getattr(_state, "default", None) is None or \
            _state.default.devices.size != len(jax.devices()):
        _state.default = make_mesh()
    return _state.default


def current_mesh() -> Optional[Mesh]:
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


class use_mesh:
    """Context manager making ``mesh`` the ambient mesh for sharding-aware
    APIs (Parameter.set_sharding defaults, SPMDTrainer, kvstore 'tpu')."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = []
        _state.stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _state.stack.pop()


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def data_sharding(mesh: Optional[Mesh] = None, axis: str = "dp",
                  ) -> NamedSharding:
    """Batch-dim sharding for input batches (the reference's batch slicing
    across the ctx list, SURVEY.md §3.3 row 'Data parallel')."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def local_mesh_axes(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def global_put(x, sharding):
    """``jax.device_put`` that also works when ``sharding`` spans
    processes.  Single-process (the virtual-mesh CI shape) this IS
    ``device_put``; on a multi-process mesh ``device_put`` cannot
    target non-addressable devices, so the global array is assembled
    from each process's local data instead
    (``jax.make_array_from_process_local_data``): a batch-sharded spec
    treats ``x`` as this rank's batch slice, a replicated spec expects
    every rank to pass the same full value."""
    if jax.process_count() == 1 or not hasattr(sharding, "mesh"):
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        # already pod-global: device_put reshards globals fine — it is
        # only HOST data it cannot scatter to non-addressable devices
        return jax.device_put(x, sharding)
    local = onp.asarray(x)
    return jax.make_array_from_process_local_data(sharding, local)


def _configure_cpu_collectives():
    """Point the CPU client at a real cross-process collectives backend
    BEFORE the backend initializes.  Without this the CPU platform has
    no multi-process collectives at all — every psum across ranks
    hangs/fails — which is exactly the backend limit the pre-gloo
    ``test_kvstore_dist`` multi-process tests died on.  Only applied
    when the job is explicitly pinned to CPU (``JAX_PLATFORMS=cpu``,
    the CI stand-in for a pod); TPU pods bring their own ICI/DCN
    transport.  ``MXNET_CPU_COLLECTIVES`` overrides the implementation
    name (default ``gloo``; ``none`` disables)."""
    plats = (os.environ.get("JAX_PLATFORMS") or "").lower()
    if "cpu" not in [p.strip() for p in plats.split(",")]:
        return
    impl = os.environ.get("MXNET_CPU_COLLECTIVES", "gloo")
    if impl.lower() in ("", "0", "none"):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except Exception:
        # older jaxlib without pluggable CPU collectives: leave the
        # default in place; the rendezvous still works, collectives
        # surface their own (loud) backend error
        pass


def _init_timeout_from_env():
    from ..base import parse_seconds

    t = parse_seconds("MXNET_INIT_TIMEOUT",
                      os.environ.get("MXNET_INIT_TIMEOUT", "300"))
    return t if t > 0 else None


def _init_retries_from_env():
    raw = os.environ.get("MXNET_INIT_RETRIES", "2")
    try:
        return max(int(raw), 0)
    except ValueError:
        # same loud-knob discipline as base.parse_seconds
        raise MXNetError(f"MXNET_INIT_RETRIES={raw!r}: expected an "
                         "integer")


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None,
                     initialization_timeout: Optional[float] = None,
                     retries: Optional[int] = None) -> None:
    """Multi-host bootstrap (replaces the reference's ps-lite scheduler
    rendezvous, SURVEY.md §4.4).

    Falls back to env vars so ``tools/launch.py``-style launchers work:
    ``MXNET_COORDINATOR`` (or the reference-compatible pair
    ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``), ``MXNET_NUM_WORKERS`` (or
    ``DMLC_NUM_WORKER``), ``MXNET_WORKER_ID`` (or ``DMLC_WORKER_ID``).
    No-ops when single-process and no coordinator is configured.

    Fault tolerance (ISSUE 13): when supervised by ``tools/launch.py``
    the rank starts its heartbeat BEFORE the rendezvous, so a rank
    stuck dialing a dead coordinator still reads as alive-but-waiting.
    The rendezvous itself is bounded — ``initialization_timeout``
    seconds (``MXNET_INIT_TIMEOUT``, default 300; passed through to
    ``jax.distributed`` where supported) per attempt, ``retries``
    (``MXNET_INIT_RETRIES``, default 2) extra attempts with doubling
    backoff — and a rendezvous that still cannot complete raises a
    clean ``MXNetError`` naming the coordinator and rank instead of
    blocking forever.
    """
    from .heartbeat import start_heartbeat

    start_heartbeat()
    if coordinator_address is None:
        coordinator_address = os.environ.get("MXNET_COORDINATOR")
        if coordinator_address is None:
            uri = os.environ.get("DMLC_PS_ROOT_URI")
            port = os.environ.get("DMLC_PS_ROOT_PORT")
            if uri and port:
                coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        num_processes = int(os.environ.get(
            "MXNET_NUM_WORKERS", os.environ.get("DMLC_NUM_WORKER", "1")))
    if process_id is None:
        process_id = int(os.environ.get(
            "MXNET_WORKER_ID", os.environ.get("DMLC_WORKER_ID", "0")))
    if coordinator_address is None and num_processes == 1:
        return
    if initialization_timeout is None:
        initialization_timeout = _init_timeout_from_env()
    if retries is None:
        retries = _init_retries_from_env()
    _configure_cpu_collectives()
    kwargs = dict(coordinator_address=coordinator_address,
                  num_processes=num_processes,
                  process_id=process_id,
                  local_device_ids=local_device_ids)
    # older jax has no bounded init — degrade to unbounded rather than
    # TypeError (the retry loop still bounds total attempts)
    if initialization_timeout is not None and "initialization_timeout" \
            in inspect.signature(jax.distributed.initialize).parameters:
        # jax takes whole seconds: round UP so a sub-second budget
        # becomes 1s, never a truncated 0 (= immediate deadline)
        kwargs["initialization_timeout"] = max(
            math.ceil(float(initialization_timeout)), 1)
    from ..telemetry.faults import fault_point

    backoff, last = 1.0, None
    for attempt in range(retries + 1):
        try:
            # chaos hook: a `raise` fault here exercises the bounded
            # retry/backoff path deterministically on CPU; a `kill`
            # fault exercises the supervisor's dead-rank handling
            # mid-rendezvous
            fault_point("dist.init", coordinator=coordinator_address,
                        rank=process_id, attempt=attempt)
            jax.distributed.initialize(**kwargs)
            from ..telemetry.events import emit

            emit("dist_init", rank=process_id,
                 processes=num_processes, attempts=attempt + 1,
                 coordinator=coordinator_address,
                 devices=len(jax.devices()))
            return
        except Exception as e:  # rendezvous/transport failure
            # genuine double-init is a programming error to surface
            # verbatim, not a rendezvous failure to retry (jax's
            # actual message is "...should only be called once.";
            # older/other versions say "already initialized")
            if "should only be called once" in str(e) \
                    or "already initialized" in str(e):
                raise
            last = e
            # a failed connect leaves jax's global distributed state
            # assigned (verified against jax 0.4.x) — tear it down or
            # every retry (including a CALLER-level one after the
            # final attempt) dies on the double-init check instead of
            # re-dialing the coordinator
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempt < retries:
                time.sleep(backoff)
                backoff *= 2
    raise MXNetError(
        f"distributed init failed: rank {process_id}/{num_processes} "
        f"could not rendezvous with coordinator {coordinator_address} "
        f"after {retries + 1} attempt(s) of "
        f"{initialization_timeout or 'unbounded'}s each "
        f"(last error: {last!r}) — check that rank 0 is alive and the "
        "address is reachable; MXNET_INIT_TIMEOUT / MXNET_INIT_RETRIES "
        "tune the budget")


def _barrier_timeout_from_env():
    from ..base import parse_seconds

    t = parse_seconds("MXNET_BARRIER_TIMEOUT",
                      os.environ.get("MXNET_BARRIER_TIMEOUT", "0"))
    return t if t > 0 else None


def barrier(tag: str = "mxnet_barrier",
            timeout: Optional[float] = None) -> None:
    """Cross-process barrier with a bounded wait.

    ``timeout`` seconds (default ``MXNET_BARRIER_TIMEOUT``; unset/0 =
    wait forever, the pre-ISSUE-13 behavior) after which a clean
    ``MXNetError`` names the coordinator instead of the process
    blocking in the collective until an operator kills the job.  The
    kvstore ``dist_sync`` barrier routes through this, so a dead peer
    rank turns every survivor's next barrier into an error the
    supervisor can act on.

    On timeout the underlying collective cannot be cancelled — its
    daemon thread is abandoned (it dies with the process; the process
    group is unusable after a lost peer anyway).
    """
    if jax.process_count() == 1:
        return
    if timeout is None:
        timeout = _barrier_timeout_from_env()
    from jax.experimental import multihost_utils

    if not timeout:
        multihost_utils.sync_global_devices(tag)
        return
    done = threading.Event()
    err = []

    def _run():
        try:
            multihost_utils.sync_global_devices(tag)
        except Exception as e:
            err.append(e)
        finally:
            done.set()

    th = threading.Thread(target=_run, name="mxnet-barrier",
                          daemon=True)
    th.start()
    if not done.wait(timeout):
        raise MXNetError(
            f"barrier {tag!r} timed out after {timeout}s waiting on "
            f"the process group (rank {jax.process_index()} of "
            f"{jax.process_count()}, coordinator "
            f"{os.environ.get('MXNET_COORDINATOR', '?')}) — a peer "
            "rank is dead or wedged; the collective thread is "
            "abandoned")
    if err:
        raise MXNetError(f"barrier {tag!r} failed: {err[0]!r}")
