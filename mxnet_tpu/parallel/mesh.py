"""Device meshes and the multi-host bootstrap.

Reference counterpart: context groups + kvstore device lists
(``mx.gpu(i)`` lists sliced by ``DataParallelExecutorGroup``) and the
ps-lite/ZMQ node bootstrap driven by ``tools/launch.py`` env vars
(``DMLC_PS_ROOT_URI``/``DMLC_ROLE``/..., SURVEY.md §4.4).  TPU-native:
one ``jax.sharding.Mesh`` names the axes (``dp``/``tp``/``sp``/``pp``)
and XLA emits the collectives; multi-host membership comes from
``jax.distributed.initialize`` instead of a ZMQ Van.
"""
from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import jax
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["Mesh", "P", "make_mesh", "current_mesh", "default_mesh",
           "use_mesh", "named_sharding", "data_sharding",
           "replicated_sharding", "init_distributed", "local_mesh_axes"]

_state = threading.local()


def make_mesh(axes=None, devices: Optional[Sequence] = None) -> Mesh:
    """Build a named device mesh.

    ``axes``: dict ``{name: size}`` in major→minor order; at most one size
    may be ``-1`` ("fill with the remaining devices").  Defaults to a pure
    data-parallel mesh ``{'dp': n_devices}``.  For multi-host topologies put
    the cross-host axis first (major) so its collectives ride DCN while the
    minor axes stay on ICI (SURVEY.md §5.8).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    if isinstance(axes, (list, tuple)):
        axes = dict(axes)
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise MXNetError("at most one mesh axis may be -1")
    fixed = 1
    for s in sizes:
        if s != -1:
            fixed *= s
    if n % fixed:
        raise MXNetError(
            f"mesh axes {axes} do not divide {n} devices")
    if -1 in sizes:
        sizes[sizes.index(-1)] = n // fixed
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(
            f"mesh axes {dict(zip(names, sizes))} use {total} devices, "
            f"have {n}")
    arr = onp.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh() -> Mesh:
    """The ambient mesh: the active ``use_mesh`` if any, else a cached pure-DP
    mesh over all devices."""
    cur = current_mesh()
    if cur is not None:
        return cur
    if getattr(_state, "default", None) is None or \
            _state.default.devices.size != len(jax.devices()):
        _state.default = make_mesh()
    return _state.default


def current_mesh() -> Optional[Mesh]:
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


class use_mesh:
    """Context manager making ``mesh`` the ambient mesh for sharding-aware
    APIs (Parameter.set_sharding defaults, SPMDTrainer, kvstore 'tpu')."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = []
        _state.stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _state.stack.pop()


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def data_sharding(mesh: Optional[Mesh] = None, axis: str = "dp",
                  ) -> NamedSharding:
    """Batch-dim sharding for input batches (the reference's batch slicing
    across the ctx list, SURVEY.md §3.3 row 'Data parallel')."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def local_mesh_axes(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None) -> None:
    """Multi-host bootstrap (replaces the reference's ps-lite scheduler
    rendezvous, SURVEY.md §4.4).

    Falls back to env vars so ``tools/launch.py``-style launchers work:
    ``MXNET_COORDINATOR`` (or the reference-compatible pair
    ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``), ``MXNET_NUM_WORKERS`` (or
    ``DMLC_NUM_WORKER``), ``MXNET_WORKER_ID`` (or ``DMLC_WORKER_ID``).
    No-ops when single-process and no coordinator is configured.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("MXNET_COORDINATOR")
        if coordinator_address is None:
            uri = os.environ.get("DMLC_PS_ROOT_URI")
            port = os.environ.get("DMLC_PS_ROOT_PORT")
            if uri and port:
                coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        num_processes = int(os.environ.get(
            "MXNET_NUM_WORKERS", os.environ.get("DMLC_NUM_WORKER", "1")))
    if process_id is None:
        process_id = int(os.environ.get(
            "MXNET_WORKER_ID", os.environ.get("DMLC_WORKER_ID", "0")))
    if coordinator_address is None and num_processes == 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
