"""Legacy Module API (reference ``python/mxnet/module/``; SURVEY.md §3.2
"Module API (legacy)" row, §4.3 call stack)."""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
