"""BaseModule — the fit/score/predict epoch-loop protocol.

Reference surface: ``python/mxnet/module/base_module.py`` (SURVEY.md §4.3):
``fit()`` = epoch loop of forward_backward/update/metric/callbacks
(Speedometer), eval at epoch end, checkpoint callbacks.
"""
from __future__ import annotations

import logging
import time

from ..base import MXNetError
from .. import metric as metric_mod
from .. import ndarray as nd
from ..model import BatchEndParam


def _as_metric(m):
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


class BaseModule:
    """Abstract module: subclasses implement bind/init_params/init_optimizer/
    forward/backward/update/get_outputs/update_metric."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # ------------------------------------------------------------------ #
    # abstract surface
    # ------------------------------------------------------------------ #
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # ------------------------------------------------------------------ #
    # composite operations
    # ------------------------------------------------------------------ #
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        """Evaluate on a DataIter (reference ``score``)."""
        if not self.binded or not self.params_initialized:
            raise MXNetError("score: module not bound/initialized")
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                bp = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                   eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(bp)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        """Run forward over a DataIter, concatenating outputs."""
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if batch.pad:
                outs = [o[:o.shape[0] - batch.pad] for o in outs]
            outputs.append(outs)
        if not outputs:
            return []
        if merge_batches:
            n_out = len(outputs[0])
            merged = [nd.concat(*[b[i] for b in outputs], dim=0)
                      for i in range(n_out)]
            return merged[0] if n_out == 1 else merged
        return outputs

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, initializer=None, arg_params=None,
            aux_params=None, allow_missing=False, force_rebind=False,
            force_init=False, begin_epoch=0, num_epoch=None,
            validation_metric=None):
        """THE legacy training loop (reference ``BaseModule.fit``,
        SURVEY.md §4.3)."""
        if num_epoch is None:
            raise MXNetError("fit: num_epoch is required")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        eval_metric = _as_metric(eval_metric)
        if validation_metric is None:
            validation_metric = eval_metric

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    bp = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric,
                                       locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(bp)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 batch_end_callback=None, epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
                if eval_end_callback is not None:
                    bp = BatchEndParam(epoch=epoch, nbatch=0,
                                       eval_metric=validation_metric,
                                       locals=locals())
                    for cb in _as_list(eval_end_callback):
                        cb(bp)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
