"""Module — symbol + executor training module.

Reference surface: ``python/mxnet/module/module.py`` (SURVEY.md §4.3):
``bind`` runs simple_bind (InferShape → allocate), ``init_params``,
``init_optimizer`` (kvstore), forward/backward/update.

TPU-native: one Executor per Module (no per-GPU ``DataParallelExecutorGroup``
— data parallelism is a mesh axis, SURVEY.md §3.3); the optimizer update
runs per-parameter over executor gradients exactly like
``_update_params_on_kvstore``.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .. import initializer as init_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..model import save_checkpoint, load_checkpoint
from .base_module import BaseModule


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._exec = None
        self._optimizer = None
        self._opt_states = {}
        self._data_shapes = None
        self._label_shapes = None

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return [(n, o.shape) for n, o in zip(self.output_names,
                                             self._exec.outputs)]

    # ------------------------------------------------------------------ #
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write", **kwargs):
        if self.binded and not force_rebind:
            return
        self._data_shapes = [_as_desc(d) for d in data_shapes]
        self._label_shapes = [_as_desc(l) for l in (label_shapes or [])]
        shapes = {d[0]: tuple(d[1]) for d in self._data_shapes}
        shapes.update({l[0]: tuple(l[1]) for l in self._label_shapes})
        reqs = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names:
                reqs[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or n in self._fixed_param_names:
                reqs[n] = "null"
            else:
                reqs[n] = grad_req if for_training else "null"
        if shared_module is not None and shared_module._exec is not None:
            # bucketing: share parameter arrays with the master module
            from ..symbol.symbol import infer_args, Executor
            all_shapes = infer_args(self._symbol, **shapes)
            args = {}
            for n in self._symbol.list_arguments():
                shared = shared_module._exec.arg_dict.get(n)
                if n in self._param_names and shared is not None:
                    args[n] = shared
                else:
                    args[n] = nd.zeros(all_shapes[n])
            self._exec = Executor(self._symbol, self._context, args,
                                  None, reqs)
        else:
            self._exec = self._symbol.simple_bind(ctx=self._context,
                                                  grad_req=reqs, **shapes)
        self.binded = True
        self.for_training = for_training

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, **kwargs):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params: call bind first")
        initializer = initializer or init_mod.Uniform(0.01)
        for n in self._param_names:
            arr = self._exec.arg_dict[n]
            if arg_params is not None and n in arg_params:
                arr._rebind(nd.array(arg_params[n].asnumpy()
                                     if hasattr(arg_params[n], "asnumpy")
                                     else arg_params[n])._data)
            else:
                if arg_params is not None and not allow_missing:
                    raise MXNetError(f"init_params: missing {n}")
                initializer(init_mod.InitDesc(n), arr)
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        return arg, {}

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            # reference Module.init_optimizer: default grad rescale is
            # 1/batch_size (grads are summed over the batch)
            if "rescale_grad" not in params and self._data_shapes:
                batch = self._data_shapes[0][1][0]
                if batch:
                    params["rescale_grad"] = 1.0 / batch
            optimizer = opt_mod.create(optimizer, **params)
        self._optimizer = optimizer
        idx2name = dict(enumerate(self._param_names))
        self._optimizer.param_idx2name = idx2name
        self._opt_states = {}
        self.optimizer_initialized = True

    # ------------------------------------------------------------------ #
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for n, arr in zip(self._data_names, data_batch.data):
            feed[n] = arr
        if self._label_names and data_batch.label is not None:
            for n, arr in zip(self._label_names, data_batch.label):
                feed[n] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        if self._optimizer is None:
            raise MXNetError("update: init_optimizer first")
        # fused multi-tensor apply: every parameter in one (or a few,
        # grouped) jitted dispatches — see Optimizer.multi_update
        idxs, ws, gs, ss = [], [], [], []
        for i, n in enumerate(self._param_names):
            w = self._exec.arg_dict[n]
            g = w.grad
            if g is None:
                continue
            if i not in self._opt_states:
                self._opt_states[i] = self._optimizer.create_state(i, w)
            idxs.append(i)
            ws.append(w)
            gs.append(g)
            ss.append(self._opt_states[i])
        if not idxs:
            return
        new_states = self._optimizer.multi_update(idxs, ws, gs, ss)
        for i, ns in zip(idxs, new_states):
            self._opt_states[i] = ns

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.arg_dict[n].grad for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------------ #
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        _orig_init = mod.init_params

        def init_with_loaded(initializer=None, arg_params=None,
                             aux_params=None, **kw):
            _orig_init(initializer=initializer,
                       arg_params=arg_params or arg,
                       aux_params=aux_params or aux, **kw)
        mod.init_params = init_with_loaded
        return mod


def _as_desc(d):
    """Accept DataDesc or (name, shape) tuples."""
    if hasattr(d, "name"):
        return (d.name, tuple(d.shape))
    return (d[0], tuple(d[1]))
