"""BucketingModule — per-sequence-length executors sharing parameters.

Reference surface: ``python/mxnet/module/bucketing_module.py`` (SURVEY.md
§3.2: "per-seq-len shared executors").  Each bucket key gets its own
Module whose executor SHARES the parameter NDArrays of the default bucket
(the reference's shared-memory rebind); jit's shape-keyed cache compiles one
XLA program per bucket, which is exactly the reference's per-bucket graph.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, fixed_param_names=None, state_names=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("BucketingModule needs default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._bind_args = None

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        self._bind_args = dict(for_training=for_training,
                               inputs_need_grad=inputs_need_grad)
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if not self.binded:
            raise MXNetError("switch_bucket before bind")
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes,
                        self._bind_args["for_training"],
                        self._bind_args["inputs_need_grad"],
                        shared_module=self._buckets[self._default_bucket_key])
            if self.params_initialized:
                module.params_initialized = True
            if self.optimizer_initialized:
                # share optimizer + state (params are shared NDArrays)
                master = self._buckets[self._default_bucket_key]
                module._optimizer = master._optimizer
                module._opt_states = master._opt_states
                module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, *args, **kwargs):
        self._buckets[self._default_bucket_key].init_params(*args, **kwargs)
        for key, m in self._buckets.items():
            m.params_initialized = True
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        master = self._buckets[self._default_bucket_key]
        master.init_optimizer(*args, **kwargs)
        for key, m in self._buckets.items():
            m._optimizer = master._optimizer
            m._opt_states = master._opt_states
            m.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._curr_bucket_key
        data_shapes = [(d.name if hasattr(d, "name") else d[0],
                        tuple(d.shape if hasattr(d, "shape") else d[1]))
                       for d in (data_batch.provide_data or
                                 [("data", data_batch.data[0].shape)])]
        label_shapes = None
        if data_batch.label:
            label_shapes = [(l0.name if hasattr(l0, "name") else l0[0],
                             tuple(l0.shape if hasattr(l0, "shape") else l0[1]))
                            for l0 in (data_batch.provide_label or
                                       [("softmax_label",
                                         data_batch.label[0].shape)])]
        self.switch_bucket(key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def switch_to_default(self):
        self._curr_module = self._buckets[self._default_bucket_key]
        self._curr_bucket_key = self._default_bucket_key
