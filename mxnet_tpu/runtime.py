"""``mx.runtime`` — runtime feature registry.

Reference surface: ``src/libinfo.cc`` + ``python/mxnet/runtime.py``
(SURVEY.md §3.1 "libinfo", anchor ``MXLibInfoFeatures``): compile-time
feature flags (CUDA, CUDNN, MKLDNN, DIST_KVSTORE, ...) queryable at
runtime.

TPU-native: features reflect what this build actually provides — the TPU
backend, Pallas kernels, SPMD collectives, distributed init — probed once
at first query."""
from __future__ import annotations

from collections import OrderedDict

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _probe():
    feats = OrderedDict()

    def add(name, fn):
        try:
            feats[name] = bool(fn())
        except Exception:
            feats[name] = False

    import importlib.util as iu

    import jax

    add("TPU", lambda: any(d.platform == "tpu" for d in jax.devices()))
    add("CPU", lambda: True)
    add("CUDA", lambda: any(d.platform == "gpu" for d in jax.devices()))
    add("CUDNN", lambda: False)
    add("PALLAS", lambda: iu.find_spec("jax.experimental.pallas"))
    add("XLA", lambda: True)
    add("SPMD", lambda: True)
    add("INT64_TENSOR_SIZE", lambda: bool(jax.config.jax_enable_x64))
    add("F16C", lambda: True)          # bfloat16 native on TPU
    add("BLAS_OPEN", lambda: True)     # XLA dot
    add("DIST_KVSTORE", lambda: hasattr(jax, "distributed"))
    add("OPENMP", lambda: False)
    add("MKLDNN", lambda: False)
    add("ONEDNN", lambda: False)
    add("TENSORRT", lambda: False)
    add("OPENCV", lambda: iu.find_spec("cv2"))
    add("PROFILER", lambda: True)
    add("SIGNAL_HANDLER", lambda: True)
    add("DEBUG", lambda: False)
    return feats


class Features(dict):
    """``mx.runtime.Features()`` — dict of name -> Feature."""

    _cache = None

    def __new__(cls):
        inst = super().__new__(cls)
        return inst

    def __init__(self):
        if Features._cache is None:
            Features._cache = _probe()
        super().__init__({k: Feature(k, v)
                          for k, v in Features._cache.items()})

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"feature '{feature_name}' does not exist")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
