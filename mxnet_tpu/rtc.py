"""``mx.rtc`` — runtime kernel compilation.

Reference surface: ``src/common/rtc.cc`` + ``python/mxnet/rtc.py``
(SURVEY.md §3.1 "RTC": ``mx.rtc.CudaModule(source).get_kernel(...)`` via
NVRTC).

TPU-native redesign: the runtime-compiled-kernel facility on TPU is
**Pallas** — Python kernel functions compiled to Mosaic at trace time, the
exact role NVRTC-compiled CUDA strings play on GPU.  :class:`PallasModule`
mirrors the CudaModule surface (construct with kernel source, get a named
kernel, launch on arrays); ``CudaModule`` itself raises with a pointer here,
since there is no CUDA on this target.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["PallasModule", "CudaModule"]


class _PallasKernel:
    def __init__(self, fn, name, out_shape_fn):
        self._fn = fn
        self._name = name
        self._out_shape_fn = out_shape_fn
        self._compiled = {}

    def launch(self, args, grid=(1,), block_shapes=None, out_shapes=None):
        """Run the kernel on NDArray inputs; returns NDArray output(s).

        ``out_shapes``: list of (shape, dtype) for the outputs (defaults to
        the module's out_shape_fn applied to the inputs)."""
        import jax
        from jax.experimental import pallas as pl
        import jax.numpy as jnp

        arrays = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                  for a in args]
        if out_shapes is None:
            out_shapes = self._out_shape_fn(arrays)
        out_struct = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                      for s, d in out_shapes]
        if len(out_struct) == 1:
            out_struct = out_struct[0]
        key = tuple((a.shape, str(a.dtype)) for a in arrays) + (grid,)
        if key not in self._compiled:
            kw = {} if grid == (1,) else {"grid": grid}
            # CPU backend only supports pallas in interpret mode (tests /
            # fake-mesh runs); real Mosaic lowering on TPU
            if jax.default_backend() != "tpu":
                kw["interpret"] = True
            call = pl.pallas_call(self._fn, out_shape=out_struct, **kw)
            self._compiled[key] = jax.jit(call)
        res = self._compiled[key](*arrays)
        if isinstance(res, (tuple, list)):
            return [NDArray(r) for r in res]
        return NDArray(res)

    __call__ = launch


class PallasModule:
    """TPU runtime-compiled kernels (the NVRTC/CudaModule analog).

    ``kernels``: dict name -> Pallas kernel function (refs in, refs out) —
    the Python function IS the kernel source on this target.  An optional
    ``out_shape_fns`` dict maps name -> fn(input_arrays) -> [(shape, dtype)]
    (default: first input's shape/dtype, elementwise-style).
    """

    def __init__(self, kernels, out_shape_fns=None):
        if not isinstance(kernels, dict) or not kernels:
            raise MXNetError("PallasModule needs a dict of kernel functions")
        self._kernels = dict(kernels)
        self._out_shape_fns = dict(out_shape_fns or {})

    def get_kernel(self, name, signature=None):
        """Mirror ``CudaModule.get_kernel(name, signature)`` — the signature
        string is accepted and ignored (shapes/dtypes are inferred at
        launch)."""
        if name not in self._kernels:
            raise MXNetError(f"no kernel {name!r} in module "
                             f"(have {sorted(self._kernels)})")
        fn = self._kernels[name]
        out_fn = self._out_shape_fns.get(
            name, lambda arrs: [(arrs[0].shape, arrs[0].dtype)])
        return _PallasKernel(fn, name, out_fn)


class CudaModule:
    def __init__(self, *a, **kw):
        raise MXNetError(
            "mx.rtc.CudaModule requires CUDA/NVRTC, which this TPU-native "
            "build does not target; use mx.rtc.PallasModule — Pallas kernel "
            "functions are the TPU analog of runtime-compiled CUDA strings")
