"""ctypes bindings for the native IO runtime (``native/mxtpu_io.cc``).

The native library is the TPU-framework analog of the reference's C++ data
path (SURVEY.md §3.1 "C++ data pipeline"): RecordIO parse, libjpeg decode,
threaded prefetch.  Loading is best-effort: if the ``.so`` is missing we try
one ``make`` (g++ is in the image), and otherwise everything falls back to
the pure-Python implementations — ``available()`` gates every call site.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as onp

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmxtpu_io.so")
_LIB = None
_TRIED = False


def _build():
    src_dir = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")
    if not os.path.isfile(os.path.join(src_dir, "Makefile")):
        return False
    try:
        subprocess.run(["make", "-s"], cwd=src_dir, check=True,
                       capture_output=True, timeout=120)
        return os.path.isfile(_SO)
    except Exception:
        return False


def _load():
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if not os.path.isfile(_SO) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.mxio_last_error.restype = ctypes.c_char_p
    lib.mxio_reader_open.restype = ctypes.c_void_p
    lib.mxio_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mxio_reader_count.restype = ctypes.c_int64
    lib.mxio_reader_count.argtypes = [ctypes.c_void_p]
    lib.mxio_reader_read.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.mxio_reader_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_int64)]
    lib.mxio_reader_close.argtypes = [ctypes.c_void_p]
    lib.mxio_free.argtypes = [ctypes.c_void_p]
    lib.mxio_writer_open.restype = ctypes.c_void_p
    lib.mxio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mxio_writer_write.restype = ctypes.c_int
    lib.mxio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64]
    lib.mxio_writer_close.argtypes = [ctypes.c_void_p]
    lib.mxio_decode_jpeg.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.mxio_decode_jpeg.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_int),
                                     ctypes.POINTER(ctypes.c_int),
                                     ctypes.POINTER(ctypes.c_int)]
    lib.mxio_prefetch_create.restype = ctypes.c_void_p
    lib.mxio_prefetch_create.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.mxio_prefetch_next.restype = ctypes.c_int
    lib.mxio_prefetch_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.mxio_prefetch_close.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def last_error() -> str:
    lib = _load()
    return lib.mxio_last_error().decode() if lib else "native lib unavailable"


class NativeRecordReader:
    """Random-access RecordIO reader over the native offset index."""

    def __init__(self, path: str, idx_path: str = ""):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        self._h = lib.mxio_reader_open(path.encode(), idx_path.encode())
        if not self._h:
            raise IOError(last_error())

    def __len__(self):
        return self._lib.mxio_reader_count(self._h)

    def read(self, i: int) -> bytes:
        n = ctypes.c_int64()
        p = self._lib.mxio_reader_read(self._h, i, ctypes.byref(n))
        if not p:
            raise IOError(last_error())
        try:
            return ctypes.string_at(p, n.value)
        finally:
            self._lib.mxio_free(p)

    def close(self):
        if self._h:
            self._lib.mxio_reader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    def __init__(self, path: str, idx_path: str = ""):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        self._h = lib.mxio_writer_open(path.encode(), idx_path.encode())
        if not self._h:
            raise IOError(last_error())

    def write(self, buf: bytes):
        if self._lib.mxio_writer_write(self._h, buf, len(buf)) != 0:
            raise IOError("native write failed")

    def close(self):
        if self._h:
            self._lib.mxio_writer_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def decode_jpeg(buf: bytes, want_color: bool = True) -> onp.ndarray:
    """JPEG → HWC uint8 numpy via libjpeg."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    p = lib.mxio_decode_jpeg(buf, len(buf), int(want_color),
                             ctypes.byref(w), ctypes.byref(h),
                             ctypes.byref(c))
    if not p:
        raise IOError(last_error())
    try:
        arr = onp.ctypeslib.as_array(p, shape=(h.value, w.value, c.value))
        return arr.copy()
    finally:
        lib.mxio_free(p)


class NativePrefetcher:
    """Threaded read(+decode) pipeline over a NativeRecordReader.

    Yields either raw record bytes (``decode=False``) or decoded HWC uint8
    arrays (``decode=True``, records packed with IRHeader) in submission
    order.
    """

    IRHEADER_BYTES = 24  # uint32 flag | float label | uint64 id | uint64 id2

    def __init__(self, reader: NativeRecordReader, indices, num_threads=2,
                 capacity=16, decode=False):
        self._lib = reader._lib
        self._reader = reader  # keep alive
        idx = (ctypes.c_int64 * len(indices))(*indices)
        self._h = self._lib.mxio_prefetch_create(
            reader._h, idx, len(indices), num_threads, capacity,
            int(decode), self.IRHEADER_BYTES if decode else 0)
        self._decode = decode

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            data = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_int64()
            w = ctypes.c_int()
            h = ctypes.c_int()
            c = ctypes.c_int()
            rc = self._lib.mxio_prefetch_next(
                self._h, ctypes.byref(data), ctypes.byref(n), ctypes.byref(w),
                ctypes.byref(h), ctypes.byref(c))
            if rc == 0:
                raise StopIteration
            if rc < 0:
                if not self._decode:
                    # raw-mode failure = file corruption, not a bad image;
                    # silently skipping would misalign sample/label streams
                    raise IOError(last_error())
                continue  # skip undecodable image
            try:
                if self._decode:
                    arr = onp.ctypeslib.as_array(
                        data, shape=(h.value, w.value, c.value)).copy()
                    return arr
                return ctypes.string_at(data, n.value)
            finally:
                self._lib.mxio_free(data)

    def close(self):
        if self._h:
            self._lib.mxio_prefetch_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
