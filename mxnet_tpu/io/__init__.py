"""``mx.io`` — DataIter protocol and built-in iterators.

Reference surface: ``python/mxnet/io/io.py`` (SURVEY.md §3.2 "io / recordio
/ image" row, L6): ``DataIter``, ``DataBatch``, ``DataDesc``, ``NDArrayIter``,
``PrefetchingIter``, ``ResizeIter``, plus the C++-backed record iterators
(``ImageRecordIter`` here is built over the native/python RecordIO pipeline).
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, MNISTIter, ImageRecordIter)
