"""DataIter family (reference ``python/mxnet/io/io.py``; SURVEY.md L6, §4.5).

TPU-native stance: iterators produce host-side batches; device placement is a
single ``mx.nd.array`` per batch (≈ the reference's pinned-mem copy), and
``PrefetchingIter`` double-buffers on a background thread exactly like the
reference's ``dmlc::ThreadedIter`` wrapper (anchor ``PrefetcherIter``).
"""
from __future__ import annotations

import queue as _queue
import threading
import time as _time
from collections import namedtuple

import numpy as onp

from .. import telemetry
from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Description of one data/label entry (reference ``DataDesc``)."""

    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: lists of data/label NDArrays + pad/index metadata."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        lshapes = [getattr(l, "shape", None) for l in (self.label or [])]
        return f"DataBatch: data shapes: {shapes} label shapes: {lshapes}"


class DataIter:
    """Base iterator protocol: ``reset / next / iter_next / getdata /
    getlabel / getpad / getindex`` + ``provide_data/provide_label``."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        return False

    def getdata(self):
        return None

    def getlabel(self):
        return None

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty, default_name):
    """Normalize data into an ordered list of (name, numpy array)."""
    if data is None:
        if not allow_empty:
            raise MXNetError(f"{default_name} must be provided")
        return []
    if isinstance(data, (NDArray, onp.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise MXNetError(f"{default_name} must be non-empty")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError("data must be NDArray, numpy array, list, or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, onp.ascontiguousarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference ``NDArrayIter``): supports
    shuffle, ``last_batch_handle`` in {'pad','discard','roll_over'}."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise MXNetError(f"size mismatch for {k}")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = onp.arange(self.num_data)
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], str(v.dtype))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], str(v.dtype))
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and getattr(self, "_cursor", 0) > self.num_data:
            self._cursor = self._cursor - self.num_data - self.batch_size
        else:
            self._cursor = -self.batch_size

    def iter_next(self):
        self._cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self._cursor + self.batch_size <= self.num_data
        return self._cursor < self.num_data

    def _slice(self, arrays):
        start = self._cursor
        end = min(start + self.batch_size, self.num_data)
        out = []
        for _, v in arrays:
            chunk = v[self.idx[start:end]]
            if end - start < self.batch_size:  # pad by wrapping
                pad = self.batch_size - (end - start)
                chunk = onp.concatenate([chunk, v[self.idx[:pad]]], axis=0)
            out.append(nd.array(chunk, dtype=str(chunk.dtype)))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getindex(self):
        start = self._cursor
        end = min(start + self.batch_size, self.num_data)
        return self.idx[start:end]

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self._cursor + self.batch_size > self.num_data:
            return self._cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch, optionally resetting
    the inner iterator on exhaustion (reference ``ResizeIter``)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


_prefetch_tele_cache = None


def _prefetch_tele():
    """Lazy shared stall instruments (hot-path callers hold the
    instrument instead of re-looking it up per batch — the
    ``_ring_tele`` pattern from gluon/data/dataloader.py)."""
    global _prefetch_tele_cache
    if _prefetch_tele_cache is None:
        _prefetch_tele_cache = {
            "stalls": telemetry.counter("io_prefetch_stalls_total"),
            "stall_s": telemetry.histogram(
                "io_prefetch_stall_seconds"),
        }
    return _prefetch_tele_cache


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (reference
    ``PrefetchingIter`` ≈ ``dmlc::ThreadedIter`` double buffering).

    ``device`` (a ``Context``, ``jax.Device``, ``jax.sharding.Sharding``,
    or a list of contexts/devices) extends the reference semantics with
    the TPU-native H2D stage: the producer thread places every batch's
    data/label on device as it is prefetched, so the async copy of batch
    ``k+1`` overlaps step ``k`` — a device list lands each batch
    pre-sharded along the batch axis in ONE ``device_put``.
    ``MXNET_DEVICE_PREFETCH=0`` drops the producer thread entirely
    (legacy synchronous pull + inline placement, bit-identical values)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2, device=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        from ..gluon.data.dataloader import _env_device_prefetch
        from ..ndarray.ndarray import _placement_target
        self._target = _placement_target(device)
        # the escape hatch governs the DEVICE ring only: a device-less
        # PrefetchingIter keeps its reference host-side producer thread
        self._sync = self._target is not None and _env_device_prefetch() <= 0
        self._err = None
        self._depth = prefetch_depth
        self._queue = None
        self._thread = None
        self._stop = threading.Event()
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype, d.layout)
                     for d in it.provide_data]
                    for r, it in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype, d.layout)
                     for d in it.provide_label]
                    for r, it in zip(self.rename_label, self.iters)], [])

    def _pull(self):
        """One host pull + async device placement (raises StopIteration)."""
        batches = [it.next() for it in self.iters]
        if self._target is not None:
            batches = [self._place_batch(b) for b in batches]
        return batches

    def _place_batch(self, batch):
        from ..ndarray.ndarray import to_device
        return DataBatch(data=to_device(batch.data, self._target),
                         label=to_device(batch.label, self._target)
                         if batch.label is not None else None,
                         pad=batch.pad, index=batch.index,
                         bucket_key=batch.bucket_key,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _producer(self):
        while not self._stop.is_set():
            try:
                batches = self._pull()
            except StopIteration:
                self._queue.put(None)
                return
            except BaseException as e:  # deliver to the consumer — a dead
                self._err = e           # producer must not hang next()
                self._queue.put(None)
                return
            self._queue.put(batches)

    def _start(self):
        if self._sync:  # MXNET_DEVICE_PREFETCH=0: no producer thread
            return
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop.clear()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def reset(self):
        if not self._sync:
            self._stop.set()
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=5)
        for it in self.iters:
            it.reset()
        self._start()

    def next(self):
        if self._sync:
            batches = self._pull()  # StopIteration propagates
        else:
            # ISSUE 9 pipeline telemetry: a consumer-side stall means
            # the producer thread wasn't a batch ahead — input-bound
            if self._queue.empty():
                tele = _prefetch_tele()
                tele["stalls"].inc()
                t0 = _time.perf_counter()
                batches = self._queue.get()
                tele["stall_s"].observe(_time.perf_counter() - t0)
            else:
                batches = self._queue.get()
            if batches is None:
                if self._err is not None:
                    err, self._err = self._err, None
                    raise err
                raise StopIteration
        data = sum([b.data for b in batches], [])
        label = sum([(b.label or []) for b in batches], [])
        return DataBatch(data=data, label=label or None, pad=batches[0].pad,
                         index=batches[0].index)

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False

    def __del__(self):
        self._stop.set()


class CSVIter(DataIter):
    """CSV file iterator (reference C++ ``CSVIter``; numpy-backed here)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",", dtype=onp.float32,
                           ndmin=2).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = onp.loadtxt(label_csv, delimiter=",", dtype=onp.float32,
                                ndmin=2).reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = onp.zeros((data.shape[0],), dtype=onp.float32)
        self._inner = NDArrayIter(data, label, batch_size=batch_size,
                                  last_batch_handle="pad" if round_batch else "discard",
                                  data_name=data_name, label_name=label_name)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference C++ ``MNISTIter``)."""

    def __init__(self, image, label, batch_size=1, shuffle=False, flat=False,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        import gzip
        import struct as _struct

        def _read(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic, = _struct.unpack(">I", f.read(4))
                ndim = magic & 0xFF
                dims = _struct.unpack(f">{ndim}I", f.read(4 * ndim))
                return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(dims)

        imgs = _read(image).astype(onp.float32) / 255.0
        labels = _read(label).astype(onp.float32)
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs[:, None, :, :]
        self._inner = NDArrayIter(imgs, labels, batch_size=batch_size,
                                  shuffle=shuffle, data_name=data_name,
                                  label_name=label_name)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224), batch_size=1,
                    shuffle=False, preprocess_threads=4, prefetch_buffer=2,
                    device=None, **kwargs):
    """RecordIO image iterator (reference C++ ``ImageRecordIter``, SURVEY.md
    §4.5).  Built from :class:`mxnet_tpu.image.ImageIter` wrapped in
    :class:`PrefetchingIter` for background decode — the role the reference's
    OMP decode pool + ``PrefetcherIter`` play.  Honors the same keyword
    surface (augmentation kwargs pass through); ``device=`` adds the
    TPU-native H2D overlap stage (batches arrive device-resident)."""
    from ..image import ImageIter
    kwargs.pop("path_imgidx", None)
    inner = ImageIter(batch_size=batch_size, data_shape=data_shape,
                      path_imgrec=path_imgrec, shuffle=shuffle, **kwargs)
    if (prefetch_buffer and prefetch_buffer > 0) or device is not None:
        return PrefetchingIter(inner, prefetch_depth=max(1, prefetch_buffer),
                               device=device)
    return inner
