"""``mx.amp`` — automatic mixed precision (reference
``python/mxnet/contrib/amp/``; SURVEY.md §3.2 "AMP" row)."""
from .amp import (init, init_trainer, scale_loss, convert_model,
                  convert_hybrid_block, _uninit)
from .loss_scaler import LossScaler
from . import lists
