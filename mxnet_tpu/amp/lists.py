"""AMP cast-policy lists (reference
``python/mxnet/contrib/amp/lists/symbol_fp16.py``; SURVEY.md §3.2 "AMP":
"FP16_FUNCS/FP32_FUNCS/CONDITIONAL lists insert amp_cast/amp_multicast").

TPU note: the low-precision target defaults to **bfloat16** — the MXU's
native input dtype, with fp32 exponent range (so loss scaling is optional);
``float16`` is supported for parity and does need the scaler.
"""

# compute-bound ops that run in low precision (MXU-shaped matmuls/convs)
TARGET_DTYPE_OPS = [
    "FullyConnected", "Convolution", "Deconvolution",
    "dot", "batch_dot", "matmul", "linalg_gemm2",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "flash_attention", "fused_rnn",
]

# numerically-sensitive ops pinned to fp32
FP32_OPS = [
    "softmax", "log_softmax", "softmin", "SoftmaxOutput",
    "SoftmaxActivation", "CTCLoss", "MakeLoss",
    "LayerNorm", "InstanceNorm", "GroupNorm", "RMSNorm", "_BatchNormStats",
    "L2Normalization", "norm",
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "erf", "erfinv", "gamma", "gammaln",
    "mean", "sum", "nansum", "prod", "nanprod", "smooth_l1",
]

# elementwise combiners: cast every input to the widest input dtype
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "broadcast_hypot", "add_n", "concat", "stack", "where",
]
