"""Dynamic loss scaler (reference ``contrib/amp/loss_scaler.py``:
"×2 every 2000 steps, ÷2 on overflow detected by multi_all_finite")."""
from __future__ import annotations


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any parameter gradient is non-finite."""
        from .. import ndarray as nd
        grads = [p.grad() for p in params
                 if p.grad_req != "null" and p._data is not None
                 and p._data._grad is not None]
        if not grads:
            return False
        ok = nd.multi_all_finite(grads, num_arrays=len(grads))
        return float(ok.asnumpy()[0]) == 0.0

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
