"""``mx.contrib`` (reference ``python/mxnet/contrib/``): quantization
driver + amp re-export (the reference hosts AMP under contrib)."""
from . import quantization
from .. import amp  # reference path: mx.contrib.amp
