"""Post-training INT8 quantization driver (reference
``python/mxnet/contrib/quantization.py``; SURVEY.md §3.1/§3.2
"quantization": calibration collectors + ``quantize_net``).

Flow (reference ``quantize_net``): run calibration batches through the
fp32 net collecting per-layer input ranges (min-max or KL-entropy), then
swap compute-heavy layers for quantized variants.  Dense layers become
:class:`QuantizedDense` and Conv2D layers :class:`QuantizedConv2D` —
weights pre-quantized to int8 (per-output-channel scales for conv),
activations quantized with the calibrated range, int8×int8→int32 MXU
compute, dequantized output.
"""
from __future__ import annotations

import logging

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ops.quantization import optimal_threshold_kl

__all__ = ["quantize_net", "QuantizedDense", "QuantizedConv2D",
           "LayerOutputCollector"]


class LayerOutputCollector:
    """Collect per-layer input statistics via forward pre-hooks
    (reference ``_LayerOutputCollector`` / ``_LayerOutputMinMaxCollector``)."""

    def __init__(self, mode="naive", num_bins=8001):
        if mode not in ("naive", "entropy"):
            raise MXNetError("calib_mode must be 'naive' or 'entropy'")
        self.mode = mode
        self.num_bins = num_bins
        self.stats = {}  # layer name -> dict

    def hook(self, name):
        def _pre_hook(block, inputs):
            x = inputs[0]
            arr = x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)
            st = self.stats.setdefault(
                name, {"amax": 0.0, "hist": None, "edges": None})
            amax = float(onp.abs(arr).max())
            grew = amax > st["amax"]
            st["amax"] = max(st["amax"], amax)
            if self.mode == "entropy":
                rng = (-st["amax"] - 1e-12, st["amax"] + 1e-12)
                hist, edges = onp.histogram(arr, bins=self.num_bins,
                                            range=rng)
                hist = hist.astype(onp.float64)
                if st["hist"] is None:
                    st["hist"], st["edges"] = hist, edges
                elif grew:
                    # range widened: re-bin the accumulated histogram onto
                    # the new edges (old bin centers carry old counts)
                    centers = (st["edges"][:-1] + st["edges"][1:]) / 2
                    rebinned, _ = onp.histogram(centers, bins=self.num_bins,
                                                range=rng,
                                                weights=st["hist"])
                    st["hist"] = rebinned + hist
                    st["edges"] = edges
                else:
                    st["hist"] += hist
        return _pre_hook

    def threshold(self, name):
        st = self.stats[name]
        if self.mode == "entropy" and st["hist"] is not None:
            return optimal_threshold_kl(st["hist"], st["edges"])
        return st["amax"]


class QuantizedDense(HybridBlock):
    """INT8 Dense: w int8 (pre-quantized), x quantized per calibrated
    range, int32 accumulation, fp32 output."""

    def __init__(self, dense: nn.Dense, input_threshold: float, **kwargs):
        super().__init__(**kwargs)
        w = dense.weight.data()
        w_np = w.asnumpy()
        self._w_amax = float(onp.abs(w_np).max()) or 1e-12
        qw = onp.clip(onp.round(w_np * (127.0 / self._w_amax)),
                      -127, 127).astype(onp.int8)
        self._qweight = nd.array(qw, dtype="int8")
        self._bias = dense.bias.data() if dense.bias is not None else None
        self._x_amax = float(input_threshold) or 1e-12
        self._units = dense._units
        self._flatten = dense._flatten
        self._act = dense.act  # keep the fused activation, if any

    def hybrid_forward(self, F, x):
        if self._flatten and x.ndim > 2:
            x = x.reshape((x.shape[0], -1))
        # the same symmetric-int8 scheme as quantize_v2, with the calibrated
        # activation range
        qx, _, _ = F._contrib_quantize_v2(x, min_calib_range=-self._x_amax,
                                          max_calib_range=self._x_amax)
        acc = F.quantized_matmul_int8(qx, self._qweight, transpose_b=True)
        out = acc.astype("float32") * (self._x_amax * self._w_amax /
                                       (127.0 * 127.0))
        if self._bias is not None:
            out = out + self._bias
        if self._act is not None:
            out = self._act(out)
        return out

    def __repr__(self):
        return f"QuantizedDense({self._units}, int8)"


class QuantizedConv2D(HybridBlock):
    """INT8 Conv2D: per-output-channel int8 weights, calibrated activation
    range, int32 accumulation (reference ``_contrib_quantized_conv``)."""

    def __init__(self, conv: nn.Conv2D, input_threshold: float, **kwargs):
        super().__init__(**kwargs)
        w_np = conv.weight.data().asnumpy()             # (O, I, kh, kw)
        o = w_np.shape[0]
        w_amax = onp.abs(w_np).reshape(o, -1).max(axis=1)
        w_amax = onp.where(w_amax > 0, w_amax, 1e-12)
        qw = onp.clip(onp.round(w_np * (127.0 / w_amax)[:, None, None,
                                                        None]),
                      -127, 127).astype(onp.int8)
        self._qweight = nd.array(qw, dtype="int8")
        self._w_amax = nd.array(w_amax.astype(onp.float32))
        self._bias = conv.bias.data() if conv.bias is not None else None
        self._x_amax = float(input_threshold) or 1e-12
        self._stride = conv._stride
        self._pad = conv._pad
        self._dilate = conv._dilate
        self._groups = conv._groups
        self._channels = conv._channels
        self._act = conv.act

    def hybrid_forward(self, F, x):
        qx, _, _ = F._contrib_quantize_v2(x, min_calib_range=-self._x_amax,
                                          max_calib_range=self._x_amax)
        acc = F.quantized_conv_int8(qx, self._qweight, stride=self._stride,
                                    pad=self._pad, dilate=self._dilate,
                                    num_group=self._groups)
        scale = self._w_amax.reshape((1, -1, 1, 1)) * \
            (self._x_amax / (127.0 * 127.0))
        out = acc.astype("float32") * scale
        if self._bias is not None:
            out = out + self._bias.reshape((1, -1, 1, 1))
        if self._act is not None:
            out = self._act(out)
        return out

    def __repr__(self):
        return f"QuantizedConv2D({self._channels}, int8, per-channel)"


def _walk_replace(block, collector, exclude):
    for name, child in list(block._children.items()):
        path = child.name
        quantizable = isinstance(child, (nn.Dense, nn.Conv2D))
        if quantizable and path not in exclude \
                and path in collector.stats:
            if isinstance(child, nn.Dense):
                q = QuantizedDense(child, collector.threshold(path))
            else:
                q = QuantizedConv2D(child, collector.threshold(path))
            block._children[name] = q
            # keep any attribute alias (self.fc = Dense(...)) pointing at
            # the quantized replacement
            for attr, val in list(block.__dict__.items()):
                if val is child:
                    object.__setattr__(block, attr, q)
        else:
            _walk_replace(child, collector, exclude)


def quantize_net(network, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=None,
                 num_calib_batches=None, logger=logging):
    """Quantize a Gluon net post-training (reference ``quantize_net``).

    ``calib_data``: iterable of input batches (NDArray or (x, y) tuples).
    Returns the net with Dense/Conv2D layers swapped for their int8
    variants."""
    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported")
    if calib_data is None:
        raise MXNetError("quantize_net requires calibration data")
    exclude = set(exclude_layers or [])
    collector = LayerOutputCollector(mode=calib_mode)

    hooks = []
    hybrid_state = []  # (block, was_active) — calibration must see real
    # arrays, not tracers, so hybridized blocks run imperatively during it

    def attach(block):
        if isinstance(block, HybridBlock):
            # _auto_jit too: a block whose pre-calibration forward
            # auto-jitted would re-trace here with the collector hooks
            # attached, and the hooks would materialize tracers
            hybrid_state.append((block, block._active, block._auto_jit))
            block._active = False
            block._auto_jit = False
            block._cached_op = None
        for child in block._children.values():
            if isinstance(child, (nn.Dense, nn.Conv2D)):
                hooks.append(child.register_forward_pre_hook(
                    collector.hook(child.name)))
            attach(child)

    attach(network)
    try:
        for i, batch in enumerate(calib_data):
            if num_calib_batches is not None and i >= num_calib_batches:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            network(x)
    finally:
        for h in hooks:
            h.detach()
        for block, was_active, was_auto in hybrid_state:
            block._active = was_active
            block._auto_jit = was_auto
            block._cached_op = None  # stale fp32 trace must not survive
    _walk_replace(network, collector, exclude)
    logger.info("quantize_net: %d layers calibrated (%s mode)",
                len(collector.stats), calib_mode)
    return network
