"""``mx.sym`` — symbolic graph namespace over the shared op registry
(reference ``python/mxnet/symbol/``; SURVEY.md §3.2 "symbol module").

Op builders (``mx.sym.FullyConnected`` …) are generated from the same
registry that serves ``mx.nd`` — one table, three namespaces (SURVEY.md §7).
"""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     Executor, capture, current_capture, infer_args,
                     _make_builder)
from ..ops import registry as _registry

# ensure the op corpus is registered before namespace generation
from .. import ndarray as _nd  # noqa: F401

# wire the capture hook into the dispatch path
_registry._capture_get = current_capture


def __getattr__(name):
    try:
        _registry.get_op(name)
    except Exception:
        raise AttributeError(
            f"module 'mxnet_tpu.symbol' has no attribute {name!r}")
    b = _make_builder(name)
    globals()[name] = b
    return b


def zeros(shape, dtype="float32", name=None):
    """Constant-from-attrs symbol (via full_like over a variable is not
    possible without an input; use Variable + bind instead)."""
    raise NotImplementedError(
        "mx.sym.zeros: bind a Variable instead (XLA folds constants)")
