"""Symbol — the symbolic graph IR.

Reference surface: ``python/mxnet/symbol/symbol.py`` + nnvm ``Graph``
(SURVEY.md §3.1 "nnvm", §3.2 "symbol module", L4): ``Variable``, op
composition, ``list_arguments/list_outputs``, ``infer_shape``,
``tojson/save/load``, ``bind/simple_bind`` → ``Executor``, symbol
composition ``sym2(data=sym1)``, ``Group``.

TPU-native redesign: a Symbol node names an op in the SAME registry the
imperative path uses (SURVEY.md §7 "Op registry" — one table serves
``mx.nd``, ``mx.np`` and ``mx.sym``); execution walks the graph through
``ops.registry.invoke`` so autograd and jit treatment are identical to
imperative code.  The reference's graph passes disappear: shape/type
inference is ``jax.eval_shape`` over the walked graph, memory planning and
fusion belong to XLA.

Graphs also arise by *capture* (``mxnet_tpu.symbol.capture``): the
imperative dispatch path records one node per invoke — this is how
``HybridBlock.export`` obtains the graph, mirroring the reference where the
autograd tape IS an nnvm graph (SURVEY.md §3.1 "Imperative runtime").
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "Executor", "capture", "current_capture"]

_JSON_TYPES = (str, int, float, bool, type(None))


def _jsonable(v):
    if isinstance(v, _JSON_TYPES):
        return True
    if isinstance(v, (list, tuple)):
        return all(_jsonable(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _jsonable(x) for k, x in v.items())
    return False


class _Node:
    """One graph node.  ``op is None`` ⇒ variable (reference "null" op)."""

    __slots__ = ("op", "name", "inputs", "attrs", "num_outputs")

    def __init__(self, op, name, inputs=(), attrs=None, num_outputs=None):
        self.op = op
        self.name = name
        self.inputs = list(inputs)  # [(node, out_idx)]
        self.attrs = dict(attrs or {})
        self.num_outputs = num_outputs  # lazily discovered

    def __repr__(self):
        return f"<Node {self.op or 'var'} {self.name}>"


_name_lock = threading.Lock()
_name_counter: dict = {}


def _auto_name(hint):
    with _name_lock:
        n = _name_counter.get(hint, 0)
        _name_counter[hint] = n + 1
    return f"{hint}{n}"


def _topo(heads):
    """Topological node order for the sub-graph reaching ``heads``."""
    order, seen = [], set()

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


class Symbol:
    """A set of output heads over the node DAG."""

    def __init__(self, heads):
        self._heads = list(heads)

    # -- construction ------------------------------------------------- #
    @property
    def name(self):
        return self._heads[0][0].name

    def __repr__(self):
        return f"<Symbol {' '.join(n.name for n, _ in self._heads)}>"

    def __iter__(self):
        for i in range(len(self._heads)):
            yield Symbol([self._heads[i]])

    def __getitem__(self, i):
        if isinstance(i, str):
            names = self.list_outputs()
            if i not in names:
                raise MXNetError(f"no output named {i}")
            i = names.index(i)
        if isinstance(i, int):
            if i >= len(self._heads):
                raise MXNetError("output index out of range")
            return Symbol([self._heads[i]])
        raise MXNetError("Symbol index must be int or str")

    def __len__(self):
        return len(self._heads)

    @property
    def num_outputs(self):
        return len(self._heads)

    # -- introspection ------------------------------------------------- #
    def list_arguments(self):
        return [n.name for n in _topo(self._heads) if n.op is None]

    def list_inputs(self):
        return self.list_arguments()

    def list_outputs(self):
        out = []
        for node, idx in self._heads:
            out.append(f"{node.name}_output{idx}" if (node.num_outputs or 1) > 1
                       else f"{node.name}_output" if node.op else node.name)
        return out

    def list_auxiliary_states(self):
        return []  # aux state is functional on TPU (SURVEY.md §7)

    def get_internals(self):
        heads = []
        for node in _topo(self._heads):
            heads.append((node, 0))
        return Symbol(heads)

    def attr(self, key):
        return self._heads[0][0].attrs.get(key)

    # -- composition --------------------------------------------------- #
    def __call__(self, *args, **kwargs):
        """Substitute variables: ``net(data=other_sym)`` (reference symbol
        composition)."""
        if args:
            arg_names = self.list_arguments()
            for a, nm in zip(args, arg_names):
                kwargs.setdefault(nm, a)
        for k, v in kwargs.items():
            if not isinstance(v, Symbol):
                raise MXNetError(f"composition arg {k} must be a Symbol")
        mapping = {}

        def clone(node):
            """→ (replacement_node, forced_out_idx or None)."""
            if id(node) in mapping:
                return mapping[id(node)]
            if node.op is None and node.name in kwargs:
                # a substituted variable takes BOTH node and output index of
                # the replacement head (it may be a multi-output selection)
                ent = kwargs[node.name]._heads[0]
                mapping[id(node)] = ent
                return ent
            inputs = []
            for i, idx in node.inputs:
                r, ridx = clone(i)
                inputs.append((r, ridx if ridx is not None else idx))
            new = _Node(node.op, node.name, inputs, node.attrs,
                        node.num_outputs)
            mapping[id(node)] = (new, None)
            return new, None

        heads = []
        for n, i in self._heads:
            r, ridx = clone(n)
            heads.append((r, ridx if ridx is not None else i))
        return Symbol(heads)

    # -- serialization ------------------------------------------------- #
    def tojson(self, remove_amp_cast=True):
        nodes = _topo(self._heads)
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.op is None else n.op,
                "name": n.name,
                "attrs": {k: v if _jsonable(v) else repr(v)
                          for k, v in n.attrs.items()},
                "inputs": [[nid[id(i)], idx, 0] for i, idx in n.inputs],
                **({"num_outputs": n.num_outputs}
                   if n.num_outputs and n.num_outputs > 1 else {}),
            })
        graph = {
            "nodes": jnodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.op is None],
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": [[nid[id(n)], idx, 0] for n, idx in self._heads],
            "attrs": {"mxnet_version": ["str", "mxnet_tpu-0.1"]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname, remove_amp_cast=True):
        with open(fname, "w") as f:
            f.write(self.tojson(remove_amp_cast=remove_amp_cast))

    # -- shape/type inference ------------------------------------------ #
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux = self._infer(False, *args, **kwargs)
        return arg_shapes, out_shapes, aux

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self._infer(False, *args, **kwargs)
        except Exception:
            return None, None, None

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dtypes = dict(zip(arg_names, args))
        dtypes.update(kwargs)
        structs = {}
        for nm in arg_names:
            dt = dtypes.get(nm, "float32")
            structs[nm] = onp.dtype(dt)
        _, outs = _abstract_eval(
            self._heads,
            {nm: jax.ShapeDtypeStruct((1,), structs[nm]) for nm in arg_names})
        return ([structs[nm] for nm in arg_names],
                [onp.dtype(o.dtype) for o in outs], [])

    def _infer(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        shapes = dict(zip(arg_names, args))
        shapes.update(kwargs)
        missing = [nm for nm in arg_names if shapes.get(nm) is None]
        if missing:
            raise MXNetError(f"infer_shape: missing shapes for {missing}")
        feed = {nm: jax.ShapeDtypeStruct(tuple(shapes[nm]), onp.float32)
                for nm in arg_names}
        _, outs = _abstract_eval(self._heads, feed)
        return ([tuple(shapes[nm]) for nm in arg_names],
                [tuple(o.shape) for o in outs], [])

    # -- execution ----------------------------------------------------- #
    def eval(self, ctx=None, **kwargs):
        """Evaluate with name->NDArray bindings; returns list of NDArrays."""
        return _execute(self._heads, kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        return Executor(self, ctx, args, args_grad, grad_req)

    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        """Allocate arguments and bind (reference ``simple_bind``): shapes
        for parameters are DEDUCED from the data shapes via the InferShape
        pass (``infer_args``); only data/label shapes need to be given."""
        from .. import ndarray as nd
        arg_names = self.list_arguments()
        if any(nm not in shapes for nm in arg_names):
            all_shapes = infer_args(self, **shapes)
        else:
            all_shapes = shapes
        args = {nm: nd.zeros(all_shapes[nm]) for nm in arg_names}
        return Executor(self, ctx, args, None, grad_req)

    # -- operators ----------------------------------------------------- #
    def _binary(self, other, opname, swap=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if swap else (self, other)
            return _invoke_builder(opname, [a, b], {})
        # scalar: materialize via full_like (stays shape-polymorphic)
        const = _invoke_builder("full_like", [self], {"fill_value": other})
        a, b = (const, self) if swap else (self, const)
        return _invoke_builder(opname, [a, b], {})

    def __add__(self, o):
        return self._binary(o, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", swap=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", swap=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power")

    def __neg__(self):
        return _invoke_builder("negative", [self], {})

    # -- common methods (mirror NDArray surface) ----------------------- #
    def reshape(self, shape):
        return _invoke_builder("reshape", [self], {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return _invoke_builder("transpose", [self],
                               {"axes": tuple(axes)} if axes else {})

    def astype(self, dtype):
        return _invoke_builder("cast", [self], {"dtype": str(dtype)})

    def sum(self, axis=None, keepdims=False):
        return _invoke_builder("sum", [self],
                               {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _invoke_builder("mean", [self],
                               {"axis": axis, "keepdims": keepdims})


def Variable(name, shape=None, dtype=None, **kwargs):
    attrs = {}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    return Symbol([(_Node(None, name, attrs=attrs), 0)])


var = Variable


def Group(symbols):
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def load_json(s: str) -> Symbol:
    graph = json.loads(s)
    nodes = []
    for jn in graph["nodes"]:
        op = None if jn["op"] == "null" else jn["op"]
        node = _Node(op, jn["name"],
                     [(None, idx) for _, idx, _ in jn["inputs"]],
                     jn.get("attrs", {}), jn.get("num_outputs"))
        node.inputs = [(nodes[i], idx) for i, idx, _ in jn["inputs"]]
        nodes.append(node)
    heads = [(nodes[i], idx) for i, idx, _ in graph["heads"]]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# --------------------------------------------------------------------- #
# graph walking
# --------------------------------------------------------------------- #

def _node_attrs(node):
    attrs = {k: v for k, v in node.attrs.items()
             if not k.startswith("__")}
    # JSON round-trips tuples to lists; normalize for static hashability
    return {k: tuple(v) if isinstance(v, list) else v
            for k, v in attrs.items()}


def _node_outputs_from_invoke(node, in_arrays):
    """Run one node imperatively through the shared registry
    (autograd-aware, profiled, engine-synced)."""
    opref = _registry.get_op(node.op)
    res = _registry.invoke(opref, in_arrays, _node_attrs(node))
    outs = list(res) if isinstance(res, (list, tuple)) else [res]
    node.num_outputs = len(outs)
    return outs


def _node_outputs_abstract(node, in_arrays):
    """Run one node through its op's raw fn — the abstract-eval body.

    Deliberately NOT routed through ``_registry.invoke``: this function
    is traced (``jax.eval_shape`` in ``_abstract_eval``/``infer_args``
    and the onnx exporter), and invoke's imperative machinery —
    profiler clocks, the NaiveEngine ``block_until_ready`` sync, env
    hatches — must stay unreachable from traced code (TL001/TL007)."""
    opref = _registry.get_op(node.op)
    res = opref.fn(*in_arrays, **_node_attrs(node))
    outs = list(res) if isinstance(res, (list, tuple)) else [res]
    node.num_outputs = len(outs)
    return outs


def _execute(heads, feed, training=False):
    """Imperative walk via invoke (autograd-aware).  ``feed``:
    name -> NDArray for every variable."""
    from .. import ndarray as nd
    from ..ndarray import NDArray

    memo = {}
    outputs = []
    for node in _topo(heads):
        if node.op is None:
            if node.name not in feed:
                raise MXNetError(f"unbound variable {node.name}")
            val = feed[node.name]
            if not isinstance(val, NDArray):
                val = nd.array(val)
            memo[id(node)] = [val]
        else:
            ins = [memo[id(i)][idx] for i, idx in node.inputs]
            memo[id(node)] = _node_outputs_from_invoke(node, ins)
    for node, idx in heads:
        outputs.append(memo[id(node)][idx])
    return outputs


def _abstract_eval(heads, feed_structs):
    """jax.eval_shape over the graph (the InferShape/InferType pass)."""

    names = list(feed_structs.keys())

    def run(*arrays):
        feed = dict(zip(names, arrays))
        memo = {}
        for node in _topo(heads):
            if node.op is None:
                memo[id(node)] = [feed[node.name]]
            else:
                ins = [memo[id(i)][idx] for i, idx in node.inputs]
                memo[id(node)] = _node_outputs_abstract(node, ins)
        return [memo[id(n)][i] for n, i in heads]

    outs = jax.eval_shape(run, *[feed_structs[n] for n in names])
    return names, outs


# --------------------------------------------------------------------- #
# forward shape inference (the reference "InferShape" pass, SURVEY.md L4
# graph passes): walk the graph with known data shapes, deducing parameter
# shapes from per-op rules (the role FInferShape plays per op), then
# eval_shape each node for its outputs.
# --------------------------------------------------------------------- #

def _rule_fc(in_shapes, attrs):
    d = in_shapes[0]
    nh = int(attrs.get("num_hidden", 0))
    flatten = attrs.get("flatten", True)
    in_units = int(onp.prod(d[1:])) if flatten else d[-1]
    out = {1: (nh, in_units)}
    if len(in_shapes) > 2:
        out[2] = (nh,)
    return out


def _rule_conv(in_shapes, attrs):
    d = in_shapes[0]  # NCHW
    nf = int(attrs.get("num_filter", 0))
    kernel = tuple(attrs.get("kernel", ()))
    ng = int(attrs.get("num_group", 1))
    out = {1: (nf, d[1] // ng) + kernel}
    if len(in_shapes) > 2:
        out[2] = (nf,)
    return out


def _rule_channel(in_shapes, attrs):
    c = in_shapes[0][int(attrs.get("axis", 1))]
    return {i: (c,) for i in range(1, len(in_shapes))}


def _rule_lastdim(in_shapes, attrs):
    c = in_shapes[0][-1]
    return {i: (c,) for i in range(1, len(in_shapes))}


def _rule_embedding(in_shapes, attrs):
    return {1: (int(attrs["input_dim"]), int(attrs["output_dim"]))}


_PARAM_SHAPE_RULES = {
    "FullyConnected": _rule_fc,
    "Convolution": _rule_conv,
    "_BatchNormStats": _rule_channel,
    "InstanceNorm": lambda s, a: {i: (s[0][1],) for i in range(1, len(s))},
    "GroupNorm": lambda s, a: {i: (s[0][1],) for i in range(1, len(s))},
    "LayerNorm": _rule_lastdim,
    "RMSNorm": _rule_lastdim,
    "Embedding": _rule_embedding,
}


def infer_args(symbol, dtype="float32", **known_shapes):
    """Deduce every argument's shape given the data/label shapes.  Returns
    an OrderedDict name -> shape covering all ``list_arguments()``."""
    known = {k: tuple(v) for k, v in known_shapes.items()}
    shapes = {}   # id(node) -> [out shapes]
    arg_shapes = OrderedDict()
    for node in _topo(symbol._heads):
        if node.op is None:
            shp = known.get(node.name) or node.attrs.get("__shape__")
            if shp is not None:
                shapes[id(node)] = [tuple(shp)]
                arg_shapes[node.name] = tuple(shp)
            else:
                shapes[id(node)] = [None]
                arg_shapes[node.name] = None
            continue
        in_shapes = []
        unknown = []
        for pos, (inp, idx) in enumerate(node.inputs):
            s = shapes[id(inp)][idx]
            in_shapes.append(s)
            if s is None:
                unknown.append(pos)
        if unknown:
            rule = _PARAM_SHAPE_RULES.get(node.op)
            if rule is None or any(s is None for s in in_shapes[:1]):
                raise MXNetError(
                    f"infer_args: cannot deduce shapes of inputs {unknown} "
                    f"of op {node.op} ({node.name}); provide them explicitly")
            deduced = rule(in_shapes, node.attrs)
            for pos in unknown:
                if pos not in deduced:
                    raise MXNetError(
                        f"infer_args: op {node.op} rule left input {pos} "
                        f"unknown")
                in_shapes[pos] = deduced[pos]
                var_node = node.inputs[pos][0]
                shapes[id(var_node)] = [in_shapes[pos]]
                if var_node.op is None:
                    arg_shapes[var_node.name] = in_shapes[pos]
        # outputs via abstract eval of this single node
        structs = [jax.ShapeDtypeStruct(s, onp.dtype(dtype))
                   for s in in_shapes]
        outs = jax.eval_shape(
            lambda *xs: _node_outputs_abstract(node, list(xs)), *structs)
        shapes[id(node)] = [tuple(o.shape) for o in outs]
    missing = [k for k, v in arg_shapes.items() if v is None]
    if missing:
        raise MXNetError(f"infer_args: unresolved arguments {missing}")
    return arg_shapes


# --------------------------------------------------------------------- #
# Executor (reference GraphExecutor, src/executor/ — SURVEY.md L4):
# bind arguments, forward/backward.  Memory planning/fusion = XLA's job.
# --------------------------------------------------------------------- #

class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write"):
        from .. import ndarray as nd
        from ..ndarray import NDArray

        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        self.arg_dict = OrderedDict()
        for nm in arg_names:
            if args is None or nm not in args:
                raise MXNetError(f"bind: missing argument {nm}")
            v = args[nm]
            self.arg_dict[nm] = v if isinstance(v, NDArray) else nd.array(v)
        if isinstance(grad_req, str):
            grad_req = {nm: grad_req for nm in arg_names}
        self._grad_req = grad_req
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self._args_grad = args_grad
        for nm, arr in self.arg_dict.items():
            req = grad_req.get(nm, "null")
            if req != "null":
                arr.attach_grad(req)
        self.aux_dict = OrderedDict()
        self.outputs = []

    @property
    def grad_dict(self):
        return OrderedDict((nm, arr.grad) for nm, arr in self.arg_dict.items()
                           if self._grad_req.get(nm, "null") != "null")

    @property
    def grad_arrays(self):
        return [self.arg_dict[nm].grad
                if self._grad_req.get(nm, "null") != "null" else None
                for nm in self._symbol.list_arguments()]

    @property
    def arg_arrays(self):
        return list(self.arg_dict.values())

    def forward(self, is_train=False, **kwargs):
        from .. import autograd
        from ..ndarray import NDArray
        from .. import ndarray as nd
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k}")
            arr = v if isinstance(v, NDArray) else nd.array(v)
            self.arg_dict[k]._rebind(arr._data)
        needs_grad = any(r != "null" for r in self._grad_req.values())
        if is_train and needs_grad:
            with autograd.record():
                self.outputs = _execute(self._symbol._heads, self.arg_dict)
        else:
            with autograd.pause(train_mode=is_train):
                self.outputs = _execute(self._symbol._heads, self.arg_dict)
        return self.outputs

    def backward(self, out_grads=None):
        from .. import autograd
        if not self.outputs:
            raise MXNetError("backward before forward")
        if out_grads is not None and not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
        autograd.backward(self.outputs, out_grads)
        if self._args_grad:
            for nm, dst in self._args_grad.items():
                g = self.arg_dict[nm].grad
                if g is not None:
                    dst._rebind(g._data)

    def copy_params_from(self, arg_params, aux_params=None):
        for nm, v in arg_params.items():
            if nm in self.arg_dict:
                self.arg_dict[nm]._rebind(v._data)


# --------------------------------------------------------------------- #
# op namespace builders (mx.sym.FullyConnected(...) etc.)
# --------------------------------------------------------------------- #

# ops whose output count is known at graph-build time (reference: the op
# registry's num_outputs attr); callable receives the static attrs
_MULTI_OUTPUT = {
    "split": lambda attrs: int(attrs.get("num_outputs", 1)),
    "_BatchNormStats": lambda attrs: 5,  # out, new_mm, new_mv, mean, var
    "topk": lambda attrs: 2 if attrs.get("ret_typ") == "both" else 1,
}


def _invoke_builder(opname, sym_args, attrs, name=None):
    opref = _registry.get_op(opname)
    inputs = []
    for s in sym_args:
        if s is None:
            continue
        if not isinstance(s, Symbol):
            raise MXNetError(
                f"{opname}: symbol op inputs must be Symbols, got {type(s)}")
        if len(s._heads) != 1:
            raise MXNetError(f"{opname}: grouped symbol cannot be an input")
        inputs.append(s._heads[0])
    attrs = {k: v for k, v in attrs.items() if v is not None or k == "axis"}
    n_out = _MULTI_OUTPUT.get(opref.name, lambda a: 1)(attrs)
    # naming scope + attribute scope (reference NameManager / AttrScope)
    from ..name import current_name_manager, current_attrs
    hint = opname.lower().strip("_")
    nm = current_name_manager()
    node_name = nm.get(name, hint) if nm is not None else \
        (name or _auto_name(hint))
    # user attrs ride along under the __key__ convention (never reach fn)
    user_attrs = {f"__{k}__": v for k, v in current_attrs().items()}
    node = _Node(opref.name, node_name, inputs, {**attrs, **user_attrs},
                 num_outputs=n_out if n_out > 1 else None)
    return Symbol([(node, i) for i in range(n_out)])


import inspect as _inspect


def _make_builder(opname):
    opref = _registry.get_op(opname)
    sig = None
    try:
        sig = _inspect.signature(opref.fn)
        arr_names = [p.name for p in sig.parameters.values()
                     if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    except (ValueError, TypeError):
        arr_names = []

    def builder(*args, name=None, **kwargs):
        if opref.variadic:
            arrays = list(args[0]) if len(args) == 1 and isinstance(
                args[0], (list, tuple)) else list(args)
            return _invoke_builder(opname, arrays, kwargs, name)
        arrays = list(args)
        # tensor params passed by keyword (bias=..., gamma=...)
        for nm in arr_names[len(arrays):]:
            v = kwargs.pop(nm, None)
            arrays.append(v)
        while arrays and arrays[-1] is None:
            arrays.pop()
        return _invoke_builder(opname, arrays, kwargs, name)

    builder.__name__ = opname
    builder.__doc__ = f"Symbolic {opname} (shared registry op)."
    return builder


# --------------------------------------------------------------------- #
# capture: record imperative invokes as graph nodes (export path)
# --------------------------------------------------------------------- #

class _Capture:
    def __init__(self):
        self.value_to_entry = {}  # id(jax array) -> (node, out_idx)
        self.keepalive = []
        self.const_values = {}    # const var node name -> jax array

    def lookup(self, arr):
        return self.value_to_entry.get(id(arr))

    def mark_variable(self, name, ndarray, shape=None, dtype=None):
        node = _Node(None, name, attrs={})
        if shape is not None:
            node.attrs["__shape__"] = tuple(shape)
        self.value_to_entry[id(ndarray._data)] = (node, 0)
        self.keepalive.append(ndarray._data)
        return node

    def record(self, opref, array_args, kwargs, outs):
        attrs = {k: v for k, v in kwargs.items() if _jsonable(v)}
        if len(attrs) != len(kwargs):
            bad = set(kwargs) - set(attrs)
            raise MXNetError(
                f"capture: op {opref.name} has non-serializable attrs {bad}")
        inputs = []
        for a in array_args:
            ent = self.lookup(a._data if hasattr(a, "_data") else a)
            if ent is None:
                # unnamed input: auto-variable (e.g. a constant created
                # inside forward) — keep the value so imports can restore it
                data = a._data if hasattr(a, "_data") else a
                node = _Node(None, _auto_name("_const"), attrs={})
                ent = (node, 0)
                self.value_to_entry[id(data)] = ent
                self.keepalive.append(data)
                self.const_values[node.name] = data
            inputs.append(ent)
        node = _Node(opref.name, _auto_name(opref.name.lower().strip("_")),
                     inputs, attrs, num_outputs=len(outs))
        for i, o in enumerate(outs):
            data = o._data if hasattr(o, "_data") else o
            self.value_to_entry[id(data)] = (node, i)
            self.keepalive.append(data)
        return node

    def symbol_for(self, outputs):
        heads = []
        for o in outputs:
            ent = self.lookup(o._data if hasattr(o, "_data") else o)
            if ent is None:
                raise MXNetError("capture: output was not produced by a "
                                 "captured op")
            heads.append(ent)
        return Symbol(heads)


class capture:
    """``with capture() as cap:`` — every registry invoke records a node.

    The imperative tape-as-graph mechanism (reference ``Imperative::RecordOp``
    appending nnvm nodes, SURVEY.md §3.1)."""

    _tls = threading.local()

    def __enter__(self):
        self._prev = getattr(capture._tls, "value", None)
        capture._tls.value = _Capture()
        return capture._tls.value

    def __exit__(self, *a):
        capture._tls.value = self._prev


def current_capture():
    return getattr(capture._tls, "value", None)


def _symbol_list_attr(self, recursive=False):
    """All non-internal attrs of the head node (reference
    ``Symbol.list_attr``); ``__key__`` user attrs are returned as ``key``."""
    out = {}
    nodes = _topo(self._heads) if recursive else [self._heads[0][0]]
    for node in nodes:
        for k, v in node.attrs.items():
            if k.startswith("__") and k.endswith("__"):
                key = k[2:-2]
                out[f"{node.name}_{key}" if recursive else key] = v
    return out


def _symbol_attr_dict(self):
    """name -> attrs for every node (reference ``attr_dict``)."""
    out = {}
    for node in _topo(self._heads):
        attrs = {k[2:-2]: v for k, v in node.attrs.items()
                 if k.startswith("__") and k.endswith("__")}
        if attrs:
            out[node.name] = attrs
    return out


Symbol.list_attr = _symbol_list_attr
Symbol.attr_dict = _symbol_attr_dict
