"""``mx.engine`` — execution-engine controls.

Reference surface: ``src/engine/`` + the ``MXNET_ENGINE_TYPE`` /
``MXNET_EXEC_BULK_EXEC_*`` env vars (SURVEY.md §3.1 "Dependency engine",
§5.2, §5.6).

TPU-native reality: there is no user-visible dependency engine — JAX async
dispatch schedules, XLA fuses ("bulking" is automatic).  This module keeps
the reference's control surface meaningful:

- ``set_bulk_size`` / ``bulk``: accepted; XLA fusion subsumes op bulking,
  so these record the value and return it (graph-size hints are a no-op by
  design).
- NaiveEngine: ``MXNET_ENGINE_TYPE=NaiveEngine`` (read in ``base``) forces
  a blocking readback after every op — the reference's synchronous
  debugging engine, for bisecting async/scheduling issues.
"""
from __future__ import annotations

import contextlib
import os

from .base import is_naive_engine

__all__ = ["set_bulk_size", "bulk", "engine_type", "is_naive_engine",
           "wait_all"]

_bulk_size = int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", "15"))


def engine_type() -> str:
    """'NaiveEngine' (sync debug) or 'ThreadedEnginePerDevice' (the async
    default — here, JAX async dispatch)."""
    return "NaiveEngine" if is_naive_engine() else "ThreadedEnginePerDevice"


def set_bulk_size(size: int) -> int:
    """Reference ``mx.engine.set_bulk_size``: returns the previous value.
    XLA fusion replaces engine-level op bulking, so the value is advisory."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """``with mx.engine.bulk(16):`` — reference bulking scope (advisory)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def wait_all():
    """Block until all dispatched work is complete (``WaitForAll``)."""
    from .ndarray import waitall
    waitall()
