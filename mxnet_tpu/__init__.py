"""mxnet_tpu — a TPU-native deep-learning framework with the capabilities of
the reference (Apache MXNet lineage; see SURVEY.md).

Import as ``import mxnet_tpu as mx`` — the public surface mirrors the
reference's ``import mxnet as mx``: ``mx.nd``, ``mx.autograd``, ``mx.gluon``,
``mx.cpu()/mx.gpu()/mx.tpu()``, ``mx.random``, ``mx.optimizer``, ...
"""
__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # Honor JAX_PLATFORMS even when a sitecustomize-injected PJRT plugin
    # (the TPU tunnel) pinned jax.config.jax_platforms at import time —
    # otherwise CPU-only runs dial the tunnel (and hang when it's down).
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

from .base import MXNetError
from .context import (Context, cpu, gpu, tpu, cpu_pinned, cpu_shared,
                      num_gpus, num_tpus, current_context, gpu_memory_info)
from . import base
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random

# subpackages loaded lazily to keep import fast and avoid cycles
from importlib import import_module as _imp


def __getattr__(name):
    _lazy = {
        "gluon": ".gluon",
        "optimizer": ".optimizer",
        "initializer": ".initializer",
        "init": ".initializer",
        "metric": ".metric",
        "io": ".io",
        "kvstore": ".kvstore",
        "kv": ".kvstore",
        "profiler": ".profiler",
        "telemetry": ".telemetry",
        "runtime": ".runtime",
        "rtc": ".rtc",
        "checkpoint": ".checkpoint",
        "engine": ".engine",
        "name": ".name",
        "viz": ".visualization",
        "visualization": ".visualization",
        "util": ".util",
        "image": ".image",
        "recordio": ".recordio",
        "parallel": ".parallel",
        "models": ".models",
        "serve": ".serve",
        "np": ".numpy",
        "npx": ".numpy_extension",
        "lr_scheduler": ".optimizer.lr_scheduler",
        "callback": ".callback",
        "module": ".module",
        "symbol": ".symbol",
        "sym": ".symbol",
        "test_utils": ".test_utils",
        "amp": ".amp",
        "onnx": ".onnx",
        "contrib": ".contrib",
        "operator": ".operator",
        "model": ".model",
        "predictor": ".predictor",
    }
    if name == "AttrScope":
        from .name import AttrScope
        globals()["AttrScope"] = AttrScope
        return AttrScope
    if name in _lazy:
        mod = _imp(_lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
