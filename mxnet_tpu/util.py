"""``mx.util`` — NumPy-semantics switches and env helpers.

Reference surface: ``python/mxnet/util.py`` (SURVEY.md §3.2 "profiler /
rtc / runtime / util": ``set_np`` shape/array semantics switches,
``environment()`` test helper; §5.6 config mechanisms).

TPU-native note: jax arrays are NumPy-semantics natively, so ``np_shape``
(zero-size / zero-dim shape support) is always on; the switches only
control which *array class* (`mx.nd.NDArray` vs `mx.np.ndarray`) Gluon
blocks hand out, mirroring the reference's behavioral contract.
"""
from __future__ import annotations

import contextlib
import functools
import os
import threading

__all__ = ["is_np_shape", "is_np_array", "set_np_shape", "set_np",
           "reset_np", "np_shape", "np_array", "use_np", "use_np_array",
           "use_np_shape", "environment", "getenv", "setenv",
           "get_gpu_count", "get_gpu_memory", "default_array"]

_state = threading.local()


def _st():
    if not hasattr(_state, "np_shape"):
        _state.np_shape = True   # always-on in this framework (jax native)
        _state.np_array = False
    return _state


def is_np_shape():
    """Zero-dim/zero-size shapes enabled?  Always true here (jax arrays are
    NumPy-semantics); kept for API parity."""
    return _st().np_shape


def is_np_array():
    return _st().np_array


def set_np_shape(active):
    st = _st()
    prev, st.np_shape = st.np_shape, bool(active)
    return prev


def set_np(shape=True, array=True):
    """``mx.npx.set_np()`` — turn on NumPy semantics (array class +
    shapes)."""
    st = _st()
    st.np_shape = bool(shape)
    st.np_array = bool(array)


def reset_np():
    set_np(shape=True, array=False)


@contextlib.contextmanager
def np_shape(active=True):
    prev = set_np_shape(active)
    try:
        yield
    finally:
        set_np_shape(prev)


@contextlib.contextmanager
def np_array(active=True):
    st = _st()
    prev, st.np_array = st.np_array, bool(active)
    try:
        yield
    finally:
        st.np_array = prev


def use_np_array(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_array(True):
            return func(*args, **kwargs)
    return wrapper


def use_np_shape(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)
    return wrapper


def use_np(func):
    """Decorator: run with both np semantics active (classes too)."""
    if isinstance(func, type):
        return func  # classes pass through (jax arrays already np-style)
    return use_np_array(use_np_shape(func))


def default_array(source_array, ctx=None, dtype=None):
    """Create ndarray of the active flavor (np if ``set_np()``)."""
    if is_np_array():
        from .numpy import array as np_array_fn
        return np_array_fn(source_array, dtype=dtype, ctx=ctx)
    from .ndarray import array as nd_array_fn
    return nd_array_fn(source_array, ctx=ctx, dtype=dtype)


# --------------------------------------------------------------------------- #
# environment-variable helpers (reference ``mx.util.environment`` /
# dmlc::GetEnv pattern, SURVEY.md §5.6 — MXNET_* env overlay)
# --------------------------------------------------------------------------- #

@contextlib.contextmanager
def environment(*args):
    """``with environment('MXNET_X', '1'):`` or ``environment({k: v})`` —
    scoped env-var override (None deletes)."""
    if len(args) == 2:
        updates = {args[0]: args[1]}
    elif len(args) == 1 and isinstance(args[0], dict):
        updates = args[0]
    else:
        raise ValueError("environment(name, value) or environment(dict)")
    saved = {k: os.environ.get(k) for k in updates}
    try:
        for k, v in updates.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def getenv(name):
    return os.environ.get(name)


def setenv(name, value):
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)


def get_gpu_count():
    from .context import num_gpus
    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    from .context import gpu_memory_info
    return gpu_memory_info(gpu_dev_id)
