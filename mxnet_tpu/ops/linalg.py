"""Extended linalg operator family (reference ``src/operator/tensor/la_op.cc``,
SURVEY.md §3.1 "Operator corpus" — linalg: gemm/potrf/trsm/syrk/...).

All ops operate on the last two axes with arbitrary leading batch dims,
matching the reference's batched-linalg contract.  Implementations lower to
XLA's native triangular-solve / Cholesky / QR / eigendecomposition, which
map onto the MXU where the shapes allow; gradients come from jax autodiff
through ``jax.numpy.linalg`` / ``jax.scipy.linalg``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op, alias

__all__ = [
    "linalg_gemm", "linalg_potri", "linalg_trmm", "linalg_gelqf",
    "linalg_syevd", "linalg_sumlogdiag", "linalg_extractdiag",
    "linalg_makediag", "linalg_extracttrian", "linalg_maketrian",
    "linalg_inverse", "linalg_det", "linalg_slogdet",
]


def _t(x):
    return jnp.swapaxes(x, -1, -2)


@op("linalg_gemm")
def linalg_gemm(A, B, C, *, transpose_a=False, transpose_b=False,
                alpha=1.0, beta=1.0, axis=-2):
    """C' = alpha * op(A) @ op(B) + beta * C (reference ``linalg_gemm``)."""
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@op("linalg_trmm")
def linalg_trmm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix multiply: B' = alpha * op(tri(A)) @ B (or B @ op)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = _t(tri)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@op("linalg_potri")
def linalg_potri(A):
    """Inverse from a Cholesky factor: A is lower-triangular L with
    M = L @ L^T; returns M^{-1} (reference ``linalg_potri``)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(_t(linv), linv)


@op("linalg_gelqf")
def linalg_gelqf(A):
    """LQ factorization of a full-rank m×n (m<=n) input: A = L @ Q with
    Q orthonormal rows (reference ``linalg_gelqf``).  Returns (Q, L)."""
    # LQ(A) from QR(A^T): A^T = QR  =>  A = R^T Q^T
    q, r = jnp.linalg.qr(_t(A), mode="reduced")
    return _t(q), _t(r)


@op("linalg_syevd")
def linalg_syevd(A):
    """Symmetric eigendecomposition: A = U^T diag(L) U; returns (U, L)
    with eigenvectors as ROWS of U (reference ``linalg_syevd``)."""
    w, v = jnp.linalg.eigh(A)
    return _t(v), w


@op("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    """sum(log(diag(A))) over the last two axes (reference
    ``linalg_sumlogdiag`` — the log-det of a Cholesky factor)."""
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@op("linalg_extractdiag")
def linalg_extractdiag(A, *, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@op("linalg_makediag")
def linalg_makediag(A, *, offset=0):
    def mk(v):
        return jnp.diag(v, k=offset)
    f = mk
    for _ in range(A.ndim - 1):
        f = jax.vmap(f)
    return f(A)


@op("linalg_extracttrian")
def linalg_extracttrian(A, *, offset=0, lower=True):
    """Pack the (lower/upper) triangle into a vector, row-major, matching
    the reference's packed layout."""
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@op("linalg_maketrian")
def linalg_maketrian(A, *, offset=0, lower=True):
    """Inverse of extracttrian: unpack a vector into a triangular matrix."""
    m = A.shape[-1]
    # m = n(n+1)/2 + extra from offset; solve n for the common offset cases
    k = abs(offset)
    # n^2 + n(1 +- 2k)/... solve quadratically: count = n(n+1)/2 + k*n - k(k+1)/2 for offset>0
    # reference restricts |offset| small; brute-force n
    n = 1
    while True:
        if offset == 0:
            cnt = n * (n + 1) // 2
        elif (offset > 0) == lower:
            # triangle GROWS past the diagonal (tril k>0 / triu k<0)
            cnt = n * (n + 1) // 2 + k * n - k * (k + 1) // 2
        else:
            # triangle shrinks: (n-k)(n-k+1)/2
            cnt = n * (n + 1) // 2 - k * n + k * (k - 1) // 2
        if cnt == m:
            break
        n += 1
        if n > 10000:
            raise ValueError(f"cannot infer matrix size from {m} packed "
                             f"elements")
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


@op("linalg_inverse")
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@op("linalg_det")
def linalg_det(A):
    return jnp.linalg.det(A)


@op("linalg_slogdet")
def linalg_slogdet(A):
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


alias("det", "linalg_det")
alias("slogdet", "linalg_slogdet")
alias("inverse", "linalg_inverse")
