"""Round-2 operator-corpus extensions (SURVEY.md §3.1 "Operator corpus"):
spatial-transformer pipeline, LRN, cumulative/scan ops, indexing utilities,
bitwise family, masked softmax, and the remaining tensor ops the reference
test surface touches (``src/operator/tensor/*``, ``src/operator/nn/lrn.cc``,
``src/operator/spatial_transformer.cc``).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op, alias, get_op

__all__ = [
    "SpatialTransformer", "LRN", "cumsum", "cumprod", "batch_take",
    "digamma", "moments", "ravel_multi_index", "unravel_index",
    "masked_softmax", "masked_log_softmax", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "left_shift", "right_shift", "tril",
    "triu", "trace", "tensordot", "kron", "outer", "khatri_rao",
    "index_array", "arange_like", "allclose_op", "logsumexp",
    "log1mexp", "relu6", "hard_swish", "logaddexp", "ldexp",
    "copysign", "heaviside", "nextafter", "hypot", "floor_divide",
    "remainder", "fmod", "gcd", "lcm", "isnan", "isinf", "isfinite",
    "isposinf", "isneginf", "searchsorted", "bincount_op", "diff",
    "ediff1d", "interp_op", "trapz_op", "cross_op", "vdot_op",
    "inner_op", "polyval_op", "unique_op",
]


# --------------------------------------------------------------------------- #
# spatial transformer networks (STN): GridGenerator + BilinearSampler fused
# --------------------------------------------------------------------------- #

@op("SpatialTransformer")
def SpatialTransformer(data, loc, *, target_shape=(0, 0),
                       transform_type="affine", sampler_type="bilinear",
                       cudnn_off=False):
    """Reference anchor ``SpatialTransformer``
    (src/operator/spatial_transformer.cc): affine grid from ``loc`` (N, 6)
    then bilinear sampling of NCHW ``data`` — the STN pipeline in one op."""
    from .nn import GridGenerator, BilinearSampler
    grid = get_op("GridGenerator").fn(loc, transform_type=transform_type,
                                      target_shape=tuple(target_shape))
    return get_op("BilinearSampler").fn(data, grid)


@op("LRN")
def LRN(data, *, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization across channels (reference anchor
    ``LRN``, the AlexNet-era op): out = x / (k + a/n * sum(x^2))^b."""
    sq = jnp.square(data)                               # (N, C, H, W)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    # windowed channel sum via cumulative sums (static nsize)
    csum = jnp.cumsum(padded, axis=1)
    csum = jnp.pad(csum, ((0, 0), (1, 0), (0, 0), (0, 0)))
    win = csum[:, nsize:] - csum[:, :-nsize]
    norm = (knorm + alpha / nsize * win) ** beta
    return data / norm


# --------------------------------------------------------------------------- #
# cumulative / scan
# --------------------------------------------------------------------------- #

@op("cumsum")
def cumsum(a, *, axis=None, dtype=None):
    out = jnp.cumsum(a if axis is not None else a.reshape(-1),
                     axis=axis if axis is not None else 0)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@op("cumprod")
def cumprod(a, *, axis=None, dtype=None):
    out = jnp.cumprod(a if axis is not None else a.reshape(-1),
                      axis=axis if axis is not None else 0)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@op("logsumexp")
def logsumexp(data, *, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(data, axis=axis, keepdims=keepdims)


# --------------------------------------------------------------------------- #
# indexing utilities
# --------------------------------------------------------------------------- #

@op("batch_take")
def batch_take(a, indices):
    """Reference ``batch_take``: out[i] = a[i, indices[i]] — rows pick one
    element each (the classification-likelihood gather)."""
    idx = indices.astype(jnp.int32).reshape(-1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@op("ravel_multi_index", differentiable=False)
def ravel_multi_index(data, *, shape):
    """(ndim, n) coordinate rows -> flat indices (reference
    ``_ravel_multi_index``)."""
    strides = []
    s = 1
    for d in reversed(shape):
        strides.append(s)
        s *= d
    strides = jnp.asarray(list(reversed(strides)), data.dtype)
    return jnp.sum(data * strides[:, None], axis=0)


@op("unravel_index", differentiable=False)
def unravel_index(data, *, shape):
    """flat indices -> (ndim, n) coordinate rows (reference
    ``_unravel_index``)."""
    idx = data.astype(jnp.int64).reshape(-1)
    coords = jnp.stack(jnp.unravel_index(idx, shape), axis=0)
    return coords.astype(data.dtype)


@op("index_array", differentiable=False)
def index_array(data, *, axes=None):
    """Reference ``_contrib_index_array``: an int64 array whose value at
    position (i, j, ...) is its own index vector along ``axes``."""
    shape = data.shape
    axes = tuple(range(len(shape))) if axes is None else tuple(axes)
    comps = [jnp.broadcast_to(
        lax.broadcasted_iota(jnp.int64, shape, ax), shape) for ax in axes]
    return jnp.stack(comps, axis=-1)


@op("arange_like", differentiable=False)
def arange_like(data, *, start=0.0, step=1.0, repeat=1, axis=None):
    """Reference ``_contrib_arange_like``: arange shaped like the input
    (or its ``axis`` length)."""
    if axis is None:
        n = data.size
        m = -(-n // repeat)                     # distinct values
        out = start + step * jnp.arange(m, dtype=data.dtype)
        return jnp.repeat(out, repeat)[:n].reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=data.dtype)


@op("searchsorted", differentiable=False)
def searchsorted(a, v, *, side="left"):
    return jnp.searchsorted(a, v, side=side)


@op("unique_op", differentiable=False)
def unique_op(data, *, size=None, fill_value=0):
    """np.unique with a STATIC ``size`` (XLA needs static shapes — the
    reference's dynamic-shape unique must be bounded on TPU; pass
    ``size=`` or get the input-sized padded form)."""
    return jnp.unique(data.reshape(-1), size=size or data.size,
                      fill_value=fill_value)


# --------------------------------------------------------------------------- #
# masked softmax family (reference masked_softmax / masked_log_softmax)
# --------------------------------------------------------------------------- #

@op("masked_softmax")
def masked_softmax(data, mask=None, *, axis=-1, temperature=1.0,
                   normalize=True):
    s = data / temperature
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e30)
    p = jax.nn.softmax(s, axis=axis)
    if mask is not None:
        p = jnp.where(mask.astype(bool), p, 0.0)
    return p


@op("masked_log_softmax")
def masked_log_softmax(data, mask=None, *, axis=-1, temperature=1.0):
    s = data / temperature
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e30)
    out = jax.nn.log_softmax(s, axis=axis)
    if mask is not None:
        out = jnp.where(mask.astype(bool), out, -jnp.inf)
    return out


# --------------------------------------------------------------------------- #
# bitwise / integer ops
# --------------------------------------------------------------------------- #

@op("bitwise_and", differentiable=False)
def bitwise_and(a, b):
    return jnp.bitwise_and(a, b)


@op("bitwise_or", differentiable=False)
def bitwise_or(a, b):
    return jnp.bitwise_or(a, b)


@op("bitwise_xor", differentiable=False)
def bitwise_xor(a, b):
    return jnp.bitwise_xor(a, b)


@op("bitwise_not", differentiable=False)
def bitwise_not(a):
    return jnp.bitwise_not(a)


@op("left_shift", differentiable=False)
def left_shift(a, b):
    return jnp.left_shift(a, b)


@op("right_shift", differentiable=False)
def right_shift(a, b):
    return jnp.right_shift(a, b)


@op("gcd", differentiable=False)
def gcd(a, b):
    return jnp.gcd(a.astype(jnp.int64), b.astype(jnp.int64)).astype(a.dtype)


@op("lcm", differentiable=False)
def lcm(a, b):
    return jnp.lcm(a.astype(jnp.int64), b.astype(jnp.int64)).astype(a.dtype)


# --------------------------------------------------------------------------- #
# triangles / contractions
# --------------------------------------------------------------------------- #

@op("tril")
def tril(data, *, k=0):
    return jnp.tril(data, k=k)


@op("triu")
def triu(data, *, k=0):
    return jnp.triu(data, k=k)


@op("trace")
def trace(data, *, offset=0, axis1=0, axis2=1):
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


@op("tensordot")
def tensordot(a, b, *, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                     for x in axes)
    return jnp.tensordot(a, b, axes=axes)


@op("kron")
def kron(a, b):
    return jnp.kron(a, b)


@op("outer")
def outer(a, b):
    return jnp.outer(a, b)


@op("vdot_op")
def vdot_op(a, b):
    return jnp.vdot(a, b)


@op("inner_op")
def inner_op(a, b):
    return jnp.inner(a, b)


@op("cross_op")
def cross_op(a, b, *, axisa=-1, axisb=-1, axisc=-1, axis=None):
    return jnp.cross(a, b, axisa=axisa, axisb=axisb, axisc=axisc, axis=axis)


@op("khatri_rao", variadic=True)
def khatri_rao(*matrices):
    """Column-wise Kronecker product (reference ``khatri_rao``)."""
    out = matrices[0]
    for m in matrices[1:]:
        n = out.shape[1]
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, n)
    return out


# --------------------------------------------------------------------------- #
# pointwise additions
# --------------------------------------------------------------------------- #

@op("digamma")
def digamma(data):
    return jax.scipy.special.digamma(data)


@op("relu6")
def relu6(data):
    return jnp.clip(data, 0.0, 6.0)


@op("hard_swish")
def hard_swish(data):
    return data * jnp.clip(data + 3.0, 0.0, 6.0) / 6.0


@op("log1mexp")
def log1mexp(data):
    """log(1 - exp(x)) for x < 0, numerically stable."""
    return jnp.where(data > -0.6931471805599453,          # -log 2
                     jnp.log(-jnp.expm1(data)),
                     jnp.log1p(-jnp.exp(data)))


@op("logaddexp")
def logaddexp(a, b):
    return jnp.logaddexp(a, b)


@op("ldexp")
def ldexp(a, b):
    return a * jnp.power(2.0, b)


@op("copysign")
def copysign(a, b):
    return jnp.copysign(a, b)


@op("heaviside", differentiable=False)
def heaviside(a, b):
    return jnp.heaviside(a, b)


@op("nextafter", differentiable=False)
def nextafter(a, b):
    return jnp.nextafter(a, b)


@op("hypot")
def hypot(a, b):
    return jnp.hypot(a, b)


@op("floor_divide", differentiable=False)
def floor_divide(a, b):
    return jnp.floor_divide(a, b)


@op("remainder", differentiable=False)
def remainder(a, b):
    return jnp.remainder(a, b)


@op("fmod", differentiable=False)
def fmod(a, b):
    return jnp.fmod(a, b)


@op("isnan", differentiable=False)
def isnan(a):
    return jnp.isnan(a)


@op("isinf", differentiable=False)
def isinf(a):
    return jnp.isinf(a)


@op("isfinite", differentiable=False)
def isfinite(a):
    return jnp.isfinite(a)


@op("isposinf", differentiable=False)
def isposinf(a):
    return jnp.isposinf(a)


@op("isneginf", differentiable=False)
def isneginf(a):
    return jnp.isneginf(a)


# --------------------------------------------------------------------------- #
# statistics / numerics
# --------------------------------------------------------------------------- #

@op("moments")
def moments(data, *, axes=None, keepdims=False):
    """Reference ``moments``: (mean, variance) over ``axes`` in one op."""
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=keepdims)
    mk = mean if keepdims or ax is None else \
        jnp.expand_dims(mean, ax)
    var = jnp.mean(jnp.square(data - (mean if keepdims or ax is None
                                      else mk)), axis=ax,
                   keepdims=keepdims)
    return mean, var


@op("bincount_op", differentiable=False)
def bincount_op(data, weights=None, *, minlength=0, length=None):
    """Static-length bincount (XLA static shapes: pass ``length`` or
    ``minlength`` as the bound)."""
    n = length or minlength
    if not n:
        raise ValueError("TPU bincount needs a static length= or "
                         "minlength= bound")
    return jnp.bincount(data.reshape(-1).astype(jnp.int32),
                        weights=None if weights is None
                        else weights.reshape(-1), length=n)


@op("diff")
def diff(a, *, n=1, axis=-1):
    return jnp.diff(a, n=n, axis=axis)


@op("ediff1d")
def ediff1d(a):
    return jnp.diff(a.reshape(-1))


@op("interp_op")
def interp_op(x, xp, fp, *, left=None, right=None):
    return jnp.interp(x, xp, fp, left=left, right=right)


@op("trapz_op")
def trapz_op(y, x=None, *, dx=1.0, axis=-1):
    return jax.scipy.integrate.trapezoid(y, x=x, dx=dx, axis=axis)


@op("polyval_op")
def polyval_op(p, x):
    return jnp.polyval(p, x)


@op("allclose_op", differentiable=False)
def allclose_op(a, b, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    """Reference ``_contrib_allclose``."""
    return jnp.all(jnp.isclose(a, b, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


# reference-name aliases
alias("_ravel_multi_index", "ravel_multi_index")
alias("_unravel_index", "unravel_index")
alias("_contrib_index_array", "index_array")
alias("_contrib_arange_like", "arange_like")
alias("_contrib_allclose", "allclose_op")
alias("softmax_cross_entropy_mask", "masked_log_softmax")


# --------------------------------------------------------------------------- #
# reductions / statistics (reference tensor/broadcast_reduce_op + np mirror)
# --------------------------------------------------------------------------- #

@op("var")
def var(a, *, axis=None, ddof=0, keepdims=False):
    return jnp.var(a, axis=_ax(axis), ddof=ddof, keepdims=keepdims)


@op("std")
def std(a, *, axis=None, ddof=0, keepdims=False):
    return jnp.std(a, axis=_ax(axis), ddof=ddof, keepdims=keepdims)


@op("ptp")
def ptp(a, *, axis=None, keepdims=False):
    return jnp.ptp(a, axis=_ax(axis), keepdims=keepdims)


@op("median")
def median(a, *, axis=None, keepdims=False):
    return jnp.median(a, axis=_ax(axis), keepdims=keepdims)


@op("percentile")
def percentile(a, *, q, axis=None, keepdims=False,
               interpolation="linear"):
    return jnp.percentile(a, jnp.asarray(q), axis=_ax(axis),
                          keepdims=keepdims, method=interpolation)


@op("quantile")
def quantile(a, *, q, axis=None, keepdims=False, interpolation="linear"):
    return jnp.quantile(a, jnp.asarray(q), axis=_ax(axis),
                        keepdims=keepdims, method=interpolation)


@op("average")
def average(a, weights=None, *, axis=None):
    return jnp.average(a, axis=_ax(axis), weights=weights)


@op("nanmean")
def nanmean(a, *, axis=None, keepdims=False):
    return jnp.nanmean(a, axis=_ax(axis), keepdims=keepdims)


@op("nanstd")
def nanstd(a, *, axis=None, ddof=0, keepdims=False):
    return jnp.nanstd(a, axis=_ax(axis), ddof=ddof, keepdims=keepdims)


@op("nanvar")
def nanvar(a, *, axis=None, ddof=0, keepdims=False):
    return jnp.nanvar(a, axis=_ax(axis), ddof=ddof, keepdims=keepdims)


@op("nanmax")
def nanmax(a, *, axis=None, keepdims=False):
    return jnp.nanmax(a, axis=_ax(axis), keepdims=keepdims)


@op("nanmin")
def nanmin(a, *, axis=None, keepdims=False):
    return jnp.nanmin(a, axis=_ax(axis), keepdims=keepdims)


@op("nanargmax", differentiable=False)
def nanargmax(a, *, axis=None):
    return jnp.nanargmax(a, axis=axis)


@op("nanargmin", differentiable=False)
def nanargmin(a, *, axis=None):
    return jnp.nanargmin(a, axis=axis)


@op("count_nonzero", differentiable=False)
def count_nonzero(a, *, axis=None, keepdims=False):
    return jnp.count_nonzero(a, axis=_ax(axis), keepdims=keepdims)


@op("histogram_op", differentiable=False)
def histogram_op(data, *, bin_cnt=10, range=None):
    """Static-bin histogram (reference ``_histogram``): returns
    (counts, bin_edges)."""
    lo, hi = range if range is not None else (float(0), float(1))
    return jnp.histogram(data.reshape(-1), bins=bin_cnt, range=(lo, hi))


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


# --------------------------------------------------------------------------- #
# array manipulation
# --------------------------------------------------------------------------- #

@op("roll")
def roll(a, *, shift, axis=None):
    sh = tuple(shift) if isinstance(shift, (list, tuple)) else shift
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.roll(a, sh, axis=ax)


@op("rot90")
def rot90(a, *, k=1, axes=(0, 1)):
    return jnp.rot90(a, k=k, axes=tuple(axes))


@op("fliplr")
def fliplr(a):
    return jnp.fliplr(a)


@op("flipud")
def flipud(a):
    return jnp.flipud(a)


@op("atleast_1d")
def atleast_1d(a):
    return jnp.atleast_1d(a)


@op("atleast_2d")
def atleast_2d(a):
    return jnp.atleast_2d(a)


@op("atleast_3d")
def atleast_3d(a):
    return jnp.atleast_3d(a)


@op("hstack", variadic=True)
def hstack(*arrays):
    return jnp.hstack(list(arrays))


@op("vstack", variadic=True)
def vstack(*arrays):
    return jnp.vstack(list(arrays))


@op("dstack", variadic=True)
def dstack(*arrays):
    return jnp.dstack(list(arrays))


@op("column_stack", variadic=True)
def column_stack(*arrays):
    return jnp.column_stack(list(arrays))


@op("meshgrid", variadic=True)
def meshgrid(*arrays, indexing="xy"):
    return tuple(jnp.meshgrid(*arrays, indexing=indexing))


@op("hsplit")
def hsplit(a, *, indices_or_sections):
    return tuple(jnp.hsplit(a, indices_or_sections))


@op("vsplit")
def vsplit(a, *, indices_or_sections):
    return tuple(jnp.vsplit(a, indices_or_sections))


@op("dsplit")
def dsplit(a, *, indices_or_sections):
    return tuple(jnp.dsplit(a, indices_or_sections))


@op("moveaxis")
def moveaxis(a, *, source, destination):
    return jnp.moveaxis(a, source, destination)


@op("rollaxis")
def rollaxis(a, *, axis, start=0):
    return jnp.rollaxis(a, axis, start)


@op("nan_to_num")
def nan_to_num(a, *, copy=True, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf)


@op("resize_op")
def resize_op(a, *, new_shape):
    """np.resize semantics (cyclic repetition to the new shape)."""
    return jnp.resize(a, tuple(new_shape))


@op("broadcast_arrays", variadic=True)
def broadcast_arrays(*arrays):
    return tuple(jnp.broadcast_arrays(*arrays))


@op("squared_difference")
def squared_difference(a, b):
    return jnp.square(a - b)


@op("reset_arrays", variadic=True, differentiable=False)
def reset_arrays(*arrays, num_arrays=None):
    """Reference ``reset_arrays`` (zero a list of tensors in one engine
    op — used to clear gradient buffers)."""
    return tuple(jnp.zeros_like(a) for a in arrays)


@op("clip_global_norm", variadic=True, differentiable=False)
def clip_global_norm(*arrays, max_norm, scale=1.0):
    """gluon.utils.clip_global_norm as one fused op: rescales every array
    by min(1, max_norm/||g||_global)."""
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a.astype(jnp.float32)))
                         for a in arrays))
    ratio = jnp.minimum(1.0, max_norm / (total * scale + 1e-12))
    return tuple((a * ratio).astype(a.dtype) for a in arrays)
